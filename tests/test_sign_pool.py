"""Host-crypto pool + signature-table cache tests (ISSUE 16).

The contract under test, layer by layer:

- the pool/cache host tier (``ba_tpu.crypto.pool``) imports jax-free —
  worker processes never pay (or need) a jax import;
- pooled signing/verify is BIT-EXACT with the in-process path —
  signature tables AND verdict planes, at every worker count, because
  sharding is deterministic contiguous ranges reassembled by index
  over per-row-deterministic Ed25519;
- a dead worker degrades its shard to the in-process path, counted,
  never wedging — and a whole signed campaign over a half-dead pool
  still completes bit-exact;
- the signature-table cache returns byte-identical tables/planes on a
  hit, enforces its LRU bounds, counts hits/misses/evictions, and
  ``BA_TPU_SIGN_CACHE=0`` opts out;
- the depth-k no-blocking dispatch-count proof still holds with pool +
  cache + cross-window coalescing ALL live (cold and warm);
- the ISSUE 16 small fix — hoisting the invariant key arrays out of
  the window loop — changed no behavior: hoisted-path signatures equal
  the per-call path's byte-for-byte.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.random as jr  # noqa: E402

from ba_tpu.crypto import pool as pool_mod  # noqa: E402
from ba_tpu.crypto.signed import (  # noqa: E402
    _round_table_msgs,
    commander_keys,
    key_table_arrays,
    sign_round_tables,
    verify_host_exact,
)
from ba_tpu.parallel.pipeline import fresh_copy, pipeline_sweep  # noqa: E402
from ba_tpu.parallel.signing import SignAheadLane  # noqa: E402

from test_signed_pipeline import churn_state  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_defaults():
    """Every test leaves the process-default pool/cache as it found
    them: drained and re-derived from the (restored) env on next use."""
    yield
    pool_mod.shutdown_defaults()


def _drain(pool):
    pool.close()


# -- jax-free host tier -------------------------------------------------------


def test_pool_module_imports_jax_free():
    # A subprocess pin, not an in-process check: this suite already
    # imported jax, so only a fresh interpreter can prove the module
    # never pulls it (the pool-worker contract).
    code = (
        "import sys; import ba_tpu.crypto.pool; "
        "assert 'jax' not in sys.modules, 'jax leaked into the pool tier'; "
        "import ba_tpu.crypto.signed; "
        "assert 'jax' not in sys.modules, 'jax leaked via crypto.signed'"
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run(
        [sys.executable, "-c", code], check=True, env=env, timeout=120
    )


# -- pooled vs in-process bit-exactness ---------------------------------------


def test_pool_sign_verify_bit_exact_vs_inprocess():
    B, V, seed = 6, 2, 9
    sks, pks = commander_keys(B, seed)
    rounds = list(range(5))
    ref_sigs = np.stack(
        [sign_round_tables(sks, pks, r, V)[1] for r in rounds]
    )
    msgs = np.concatenate([_round_table_msgs(B, r, V, 0) for r in rounds])
    pks_w = np.tile(pks, (len(rounds), 1))
    # Corrupt a few signatures so the verdict planes carry real False
    # rows, not an all-True plane any bug could fake.
    sigs_bad = ref_sigs.reshape(len(rounds) * B, V, 64).copy()
    sigs_bad[3, 1, 0] ^= 0xFF
    sigs_bad[11, 0, 5] ^= 0x01
    ref_ok = verify_host_exact(pks_w, msgs, sigs_bad)
    assert not ref_ok.all() and ref_ok.any()

    pool = pool_mod.SignPool(2)
    try:
        assert pool.workers == 2

        def fallback(rs):
            return np.stack(
                [sign_round_tables(sks, pks, r, V)[1] for r in rs]
            )

        got_sigs = pool.sign_rounds(seed, B, V, 0, rounds, fallback)
        got_ok = pool.verify_rows(pks_w, msgs, sigs_bad)
    finally:
        _drain(pool)
    np.testing.assert_array_equal(got_sigs, ref_sigs)
    np.testing.assert_array_equal(got_ok, ref_ok)
    assert pool.degraded == 0


def test_pool_lane_planes_and_tables_bit_exact():
    B, wins = 5, [(0, 3), (3, 4)]
    ref_lane = SignAheadLane(B, seed=4, pool=0, cache=0)
    ref_planes = [np.asarray(p) for p in ref_lane.stage_windows(wins)]
    pool = pool_mod.SignPool(2)
    cache = pool_mod.SigTableCache(32)
    try:
        lane = SignAheadLane(B, seed=4, pool=pool, cache=cache)
        planes = [np.asarray(p) for p in lane.stage_windows(wins)]
    finally:
        _drain(pool)
    for a, b in zip(ref_planes, planes):
        np.testing.assert_array_equal(a, b)
    # TABLES too, through the cache (it holds exactly what the pool
    # signed): byte-equal to the per-round reference signer.
    for r in range(4):
        key_r = pool_mod.SigTableCache.round_key(
            lane.pks, _round_table_msgs(B, r, 2, 0)
        )
        sigs_r, ok_r = cache.get(key_r)
        np.testing.assert_array_equal(
            sigs_r, ref_lane.round_tables(r)[1]
        )
        assert ok_r is not None  # host route cached the verdicts too


# -- deterministic sharding ---------------------------------------------------


def test_sharding_deterministic_under_worker_count():
    # The shard boundaries are a pure function of (n, parts)...
    for n in (1, 2, 5, 8, 13):
        for parts in (1, 2, 3, 8):
            spans = pool_mod.SignPool._split(n, parts)
            assert spans == pool_mod.SignPool._split(n, parts)
            assert spans[0][0] == 0 and spans[-1][1] == n
            for (lo, hi), (lo2, _) in zip(spans, spans[1:]):
                assert hi == lo2 and hi > lo
    # ...and the OUTPUT is invariant under the worker count: 1, 2 and
    # 3 workers produce byte-identical tables and verdicts.
    B, V, seed, rounds = 4, 2, 17, list(range(6))
    sks, pks = commander_keys(B, seed)

    def fallback(rs):
        return np.stack([sign_round_tables(sks, pks, r, V)[1] for r in rs])

    ref = fallback(rounds)
    msgs = np.concatenate([_round_table_msgs(B, r, V, 0) for r in rounds])
    pks_w = np.tile(pks, (len(rounds), 1))
    sigs_flat = ref.reshape(len(rounds) * B, V, 64)
    ref_ok = verify_host_exact(pks_w, msgs, sigs_flat)
    for workers in (1, 2, 3):
        pool = pool_mod.SignPool(workers)
        try:
            np.testing.assert_array_equal(
                pool.sign_rounds(seed, B, V, 0, rounds, fallback), ref
            )
            np.testing.assert_array_equal(
                pool.verify_rows(pks_w, msgs, sigs_flat), ref_ok
            )
        finally:
            _drain(pool)


# -- degradation ladder -------------------------------------------------------


def test_dead_worker_degrades_counted_and_stays_bit_exact():
    B, V, seed, rounds = 4, 2, 23, list(range(4))
    sks, pks = commander_keys(B, seed)

    def fallback(rs):
        return np.stack([sign_round_tables(sks, pks, r, V)[1] for r in rs])

    ref = fallback(rounds)
    pool = pool_mod.SignPool(2)
    try:
        # Kill one worker process out from under the pool: its shard
        # must degrade to the in-process body, counted, and the result
        # must not change by a byte.
        pool._workers[0].proc.kill()
        pool._workers[0].proc.wait()
        got = pool.sign_rounds(seed, B, V, 0, rounds, fallback)
        np.testing.assert_array_equal(got, ref)
        assert pool.degraded >= 1
        assert pool.workers == 1  # the dead worker retired permanently
        # The survivor keeps serving...
        np.testing.assert_array_equal(
            pool.sign_rounds(seed, B, V, 0, rounds, fallback), ref
        )
        # ...and an all-dead pool degrades whole calls in-process.
        pool._workers[1].proc.kill()
        pool._workers[1].proc.wait()
        np.testing.assert_array_equal(
            pool.sign_rounds(seed, B, V, 0, rounds, fallback), ref
        )
        assert pool.workers == 0
    finally:
        _drain(pool)


def test_campaign_over_half_dead_pool_completes_bit_exact(monkeypatch):
    state = churn_state(4, 8)
    key = jr.key(31)
    monkeypatch.setenv("BA_TPU_SIGN_POOL", "0")
    monkeypatch.setenv("BA_TPU_SIGN_CACHE", "0")
    pool_mod.shutdown_defaults()
    ref = pipeline_sweep(
        key, fresh_copy(state), 6, signed=True, m=2,
        rounds_per_dispatch=2, collect_decisions=True,
    )
    monkeypatch.setenv("BA_TPU_SIGN_POOL", "2")
    pool_mod.shutdown_defaults()
    pool = pool_mod.default_pool()
    assert pool is not None and pool.workers == 2
    pool._workers[0].proc.kill()
    pool._workers[0].proc.wait()
    try:
        out = pipeline_sweep(
            key, fresh_copy(state), 6, signed=True, m=2,
            rounds_per_dispatch=2, collect_decisions=True,
        )
    finally:
        pool_mod.shutdown_defaults()
    np.testing.assert_array_equal(out["histograms"], ref["histograms"])
    np.testing.assert_array_equal(out["decisions"], ref["decisions"])
    assert out["counters"] == ref["counters"]
    assert pool.degraded >= 1
    assert out["stats"]["sign_pool_workers"] == 1


# -- signature-table cache ----------------------------------------------------


def test_cache_hits_are_bit_exact_and_counted():
    B, wins = 4, [(0, 2), (2, 5)]
    cache = pool_mod.SigTableCache(32)
    lane = SignAheadLane(B, seed=6, pool=0, cache=cache)
    cold = [np.asarray(p) for p in lane.stage_windows(wins)]
    assert cache.misses == 5 and cache.hits == 0
    warm = [np.asarray(p) for p in lane.stage_windows(wins)]
    assert cache.hits == 5  # every round a pure lookup the second time
    for a, b in zip(cold, warm):
        np.testing.assert_array_equal(a, b)
    assert lane.cache_hits == 5 and lane.cache_misses == 5
    # A DIFFERENT key-set never hits the first lane's entries: the pk
    # table is inside the key.
    lane2 = SignAheadLane(B, seed=7, pool=0, cache=cache)
    lane2.stage(0, 2)
    assert lane2.cache_hits == 0 and lane2.cache_misses == 2
    # ...and a second lane over the SAME key-set shares them (the
    # serving-cohort shape: repeat traffic under the shared sign seed).
    lane3 = SignAheadLane(B, seed=6, pool=0, cache=cache)
    replay = [np.asarray(p) for p in lane3.stage_windows(wins)]
    assert lane3.cache_hits == 5
    for a, b in zip(cold, replay):
        np.testing.assert_array_equal(a, b)


def test_cache_lru_bounds_and_eviction():
    cache = pool_mod.SigTableCache(max_entries=3)
    sigs = np.zeros((2, 2, 64), np.uint8)
    ok = np.ones((2, 2), bool)
    for i in range(5):
        cache.put(bytes([i]) * 32, sigs, ok)
    assert len(cache) == 3 and cache.evictions == 2
    assert cache.get(bytes([0]) * 32) is None  # oldest evicted
    assert cache.get(bytes([4]) * 32) is not None  # newest kept
    # A hit refreshes recency: touch the oldest survivor, insert one
    # more, and the UNtouched middle entry is the one to go.
    assert cache.get(bytes([2]) * 32) is not None
    cache.put(bytes([5]) * 32, sigs, ok)
    assert cache.get(bytes([3]) * 32) is None
    assert cache.get(bytes([2]) * 32) is not None
    # The byte bound trips independently of the entry bound.
    small = pool_mod.SigTableCache(max_entries=64, max_bytes=sigs.nbytes * 2)
    for i in range(4):
        small.put(bytes([i]) * 32, sigs, None)
    assert small.nbytes <= sigs.nbytes * 2 and small.evictions >= 2


def test_cache_env_optout_and_default(monkeypatch):
    monkeypatch.setenv("BA_TPU_SIGN_CACHE", "0")
    pool_mod.shutdown_defaults()
    assert pool_mod.default_cache() is None
    lane = SignAheadLane(3, seed=1)
    assert lane.cache is None
    lane.stage(0, 2)  # uncached staging still works
    assert lane.cache_hits == 0 and lane.cache_misses == 0
    monkeypatch.setenv("BA_TPU_SIGN_CACHE", "7")
    pool_mod.shutdown_defaults()
    cache = pool_mod.default_cache()
    assert cache is not None and cache.max_entries == 7
    assert SignAheadLane(3, seed=1).cache is cache


def test_pool_env_sizing(monkeypatch):
    monkeypatch.setenv("BA_TPU_SIGN_POOL", "0")
    pool_mod.shutdown_defaults()
    assert pool_mod.default_pool() is None
    assert SignAheadLane(2, seed=0).pool_workers == 0
    monkeypatch.delenv("BA_TPU_SIGN_POOL", raising=False)
    assert pool_mod.pool_size_from_env() == max(
        0, min(8, (os.cpu_count() or 1) - 1)
    )
    with pytest.raises(ValueError):
        pool_mod.SignPool(-1)
    # close() is idempotent and leaves an in-process-equivalent pool.
    pool = pool_mod.SignPool(1)
    pool.close()
    pool.close()
    assert pool.workers == 0


# -- no-blocking proof with pool + cache + coalescing live --------------------


def test_signed_no_blocking_dispatch_count_with_pool_and_cache(monkeypatch):
    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setenv("BA_TPU_SIGN_POOL", "1")
    monkeypatch.setenv("BA_TPU_SIGN_CACHE", "64")
    monkeypatch.setenv("BA_TPU_SIGN_COALESCE", "3")
    pool_mod.shutdown_defaults()
    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    B, cap, R, depth = 4, 8, 7, 3
    try:
        for leg in ("cold", "warm"):  # warm = pure cache hits
            events = []
            out = pipeline_sweep(
                jr.key(23), churn_state(B, cap), R, signed=True,
                depth=depth, rounds_per_dispatch=1,
                on_event=lambda kind, i: events.append((kind, i)),
            )
            dispatches = [i for kind, i in events if kind == "dispatch"]
            retires = [i for kind, i in events if kind == "retire"]
            assert dispatches == list(range(R))
            assert retires == list(range(R))
            first_retire = events.index(("retire", 0))
            assert events[:first_retire] == [
                ("dispatch", i) for i in range(depth + 1)
            ]
            for r in range(R - depth):
                assert events.index(("retire", r)) > events.index(
                    ("dispatch", r + depth)
                )
            assert out["stats"]["max_in_flight"] == depth + 1
            assert out["stats"]["sign_pool_workers"] == 1
            if leg == "warm":
                assert out["stats"]["sign_cache_hits"] == R
    finally:
        pool_mod.shutdown_defaults()


# -- the small fix: hoisted key arrays, no behavior change --------------------


def test_hoisted_key_arrays_no_behavior_change():
    B, V = 5, 2
    lane = SignAheadLane(B, seed=12, pool=0, cache=0)
    # The hoisted arrays are exactly the per-call derivation's.
    sk_rep, pk_rep = key_table_arrays(lane.sks, lane.pks, V)
    np.testing.assert_array_equal(lane._sk_rep, sk_rep)
    np.testing.assert_array_equal(lane._pk_rep, pk_rep)
    assert sk_rep.shape == (B * V, 32) and pk_rep.shape == (B * V, 32)
    # And the hoisted signing path (stage -> _sign_inprocess) produces
    # the SAME bytes as the unhoisted per-round signer.
    for r in (0, 3):
        np.testing.assert_array_equal(
            lane._sign_inprocess([r])[0], lane.round_tables(r)[1]
        )
    # Single-window stage() is stage_windows' degenerate case, and a
    # coalesced group equals the windows staged one at a time (fresh
    # lanes: no cache crosstalk).
    a = SignAheadLane(B, seed=12, pool=0, cache=0)
    b = SignAheadLane(B, seed=12, pool=0, cache=0)
    grouped = [np.asarray(p) for p in a.stage_windows([(0, 2), (2, 4)])]
    np.testing.assert_array_equal(grouped[0], np.asarray(b.stage(0, 2)))
    np.testing.assert_array_equal(grouped[1], np.asarray(b.stage(2, 4)))
