"""Fleet-scope causal tracing tests (ISSUE 19: trace context in
``utils/metrics.py`` + ``obs/trace.py``, the sharded sink-directory
mode, ``obs/fleet.py`` aggregation, and the context propagation through
serve batches, the sign-pool pipes and supervisor resume boundaries).

The contracts, each pinned independently:

1. **Codec + context discipline** — W3C traceparent round-trips,
   malformed/all-zero inputs degrade to None (never raise), contexts
   are thread-local and never inherited implicitly.
2. **Shard mode** — a directory sink opens one ``<pid>.<token>.jsonl``
   shard per process, led by a ``clock_anchor``; records emitted in a
   scope are stamped with the context.
3. **Merge + assembly** — shards clock-align via their anchors, merge
   deterministically (byte-identical digest), tolerate torn tails, and
   fan-in grafting reconstructs a coalesced member's request tree from
   a foreign trace.
4. **Zero added sync** — the no-blocking dispatch-count proof re-runs
   with trace propagation AND the sharded sink live, on an 8-device
   forced-host mesh, under full supervision, with
   ``jax.block_until_ready`` monkeypatched to raise.
5. **Crash-consistent trees** — a traced campaign SIGKILLed mid-flight
   (subprocess, real signal) auto-resumes in a successor, and the
   MERGED span tree stays parented: 100% of non-root spans resolve a
   parent, across the process boundary, under one trace id.
6. **Fatal trace flush** — a supervisor fatal flushes the
   ``BA_TPU_TRACE`` Chrome export BEFORE re-raising (pinned with
   ``os._exit`` in the child so atexit cannot mask a missing flush).
7. **Cross-process pool spans** — a pool worker opens its own shard
   and its ``pool_task`` span parents under the piped traceparent.
"""

import json
import os
import pathlib
import signal
import subprocess
import sys

import numpy as np
import pytest

from ba_tpu.crypto import pool as sign_pool
from ba_tpu.obs import fleet, trace
from ba_tpu.utils import metrics

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "fleet"

EXT_TRACE = "0af7651916cd43dd8448eb211c80319c"
EXT_SPAN = "b7ad6b7169203331"
EXT_TP = f"00-{EXT_TRACE}-{EXT_SPAN}-01"


@pytest.fixture
def sink_dir(tmp_path):
    """Route the process-wide sink to a temp DIRECTORY (shard mode) for
    one test, restoring the disabled default afterwards."""
    d = str(tmp_path / "sink")
    os.makedirs(d)
    d += os.sep
    metrics.configure(d)
    try:
        yield d
    finally:
        metrics.configure(None)
        metrics.set_run_id(None)


# -- codec + context discipline -----------------------------------------------


def test_traceparent_codec_round_trip():
    tp = metrics.format_traceparent(EXT_TRACE, EXT_SPAN)
    assert tp == EXT_TP
    assert metrics.parse_traceparent(tp) == (EXT_TRACE, EXT_SPAN)
    # Fresh ids are well-formed and round-trip too.
    t, s = metrics.new_trace_id(), metrics.new_span_id()
    assert len(t) == 32 and len(s) == 16
    assert metrics.parse_traceparent(
        metrics.format_traceparent(t, s)
    ) == (t, s)


def test_traceparent_parse_rejects_malformed():
    # External input must degrade to None, never raise.
    for bad in (
        "",
        "garbage",
        "00-short-b7ad6b7169203331-01",
        f"00-{EXT_TRACE}-{EXT_SPAN}",             # missing flags
        f"zz-{EXT_TRACE}-{EXT_SPAN}-01",          # bad version
        f"00-{'0' * 32}-{EXT_SPAN}-01",           # all-zero trace id
        f"00-{EXT_TRACE}-{'0' * 16}-01",          # all-zero span id
    ):
        assert metrics.parse_traceparent(bad) is None, bad
    # Lenient on case (some proxies upper-case headers): accepted, but
    # normalized to the canonical lowercase form.
    assert metrics.parse_traceparent(EXT_TP.upper()) == (
        EXT_TRACE, EXT_SPAN
    )


def test_context_is_thread_local_and_never_inherited():
    import threading

    ctx = trace.new_context()
    seen = []
    prev = metrics.set_trace_context(ctx)
    try:
        assert trace.current() == ctx
        t = threading.Thread(target=lambda: seen.append(trace.current()))
        t.start()
        t.join()
    finally:
        metrics.set_trace_context(prev)
    # The spawned thread saw NO context: propagation is explicit only.
    assert seen == [None]
    assert trace.current() is None


def test_child_context_and_scope():
    root = trace.new_context()
    assert root[2] is None
    child = trace.child_context(root)
    assert child[0] == root[0] and child[2] == root[1]
    with trace.scope(root):
        implied = trace.child_context()
        assert implied[0] == root[0] and implied[2] == root[1]
        assert trace.current_traceparent() == metrics.format_traceparent(
            root[0], root[1]
        )
    assert trace.current() is None and trace.current_traceparent() is None
    # A malformed string parent degrades to a fresh root.
    fresh = trace.new_context("not-a-traceparent")
    assert fresh[2] is None


def test_inject_scope_priority(monkeypatch):
    monkeypatch.setenv(trace.TRACE_CONTEXT_ENV, EXT_TP)
    # Env adoption: a child of the injected span.
    with trace.inject_scope() as ctx:
        assert ctx[0] == EXT_TRACE and ctx[2] == EXT_SPAN
    # An explicit traceparent beats the env.
    other = metrics.format_traceparent("ab" * 16, "cd" * 8)
    with trace.inject_scope(other) as ctx:
        assert ctx[0] == "ab" * 16 and ctx[2] == "cd" * 8
    # An already-active context beats both (pass-through, not a child).
    active = trace.new_context()
    with trace.scope(active), trace.inject_scope(other) as ctx:
        assert ctx == active


# -- shard-mode sink ----------------------------------------------------------


def test_is_dir_target():
    assert metrics.is_dir_target("some/dir" + os.sep)
    assert metrics.is_dir_target(str(REPO / "tests"))  # existing dir
    assert not metrics.is_dir_target("metrics.jsonl")
    assert not metrics.is_dir_target("-")
    assert not metrics.is_dir_target(None)
    assert not metrics.is_dir_target("")


def test_dir_sink_opens_shard_with_clock_anchor_and_stamps(sink_dir):
    ctx = trace.new_context()
    prev = metrics.set_trace_context(ctx)
    try:
        metrics.emit(
            {"event": "warmup", "v": 1, "phase": "start",
             "run_id": "run-0123456789ab", "planned": 1}
        )
    finally:
        metrics.set_trace_context(prev)
    metrics.default_sink().close()
    shards = fleet.list_shards(sink_dir)
    assert len(shards) == 1
    name, path = shards[0]
    m = fleet.SHARD_RE.match(name)
    assert m and int(m.group(1)) == os.getpid()
    recs = fleet.read_shard(path)
    assert [r["event"] for r in recs] == ["clock_anchor", "warmup"]
    anchor = recs[0]
    assert anchor["pid"] == os.getpid() and anchor["shard"] == name
    assert isinstance(anchor["perf_t"], float)
    assert isinstance(anchor["ts"], float)
    # The scope's context was stamped onto the record by the sink.
    assert recs[1]["trace_id"] == ctx[0]
    assert recs[1]["span_id"] == ctx[1]


# -- merge + assembly ---------------------------------------------------------


def _write_shard(dirpath, name, lines):
    with open(os.path.join(dirpath, name), "w") as fh:
        fh.write("\n".join(lines) + "\n")


def test_merge_aligns_clocks_and_tolerates_torn_tail(tmp_path):
    d = str(tmp_path)
    # Shard A's perf epoch is 1000 s behind shard B's: without anchor
    # alignment its records would sort 1000 s early.
    _write_shard(d, "11.aaaa.jsonl", [
        '{"event": "clock_anchor", "v": 1, "pid": 11, '
        '"shard": "11.aaaa.jsonl", "perf_t": 5.0, "ts": 2000.0}',
        '{"event": "trace_span", "v": 1, "name": "late", '
        '"trace_id": "%s", "span_id": "aaaaaaaaaaaaaaaa", '
        '"parent_id": null, "t_perf": 10.0, "dur_s": 0.1}' % EXT_TRACE,
    ])
    _write_shard(d, "22.bbbb.jsonl", [
        '{"event": "clock_anchor", "v": 1, "pid": 22, '
        '"shard": "22.bbbb.jsonl", "perf_t": 1001.0, "ts": 2001.0}',
        '{"event": "trace_span", "v": 1, "name": "early", '
        '"trace_id": "%s", "span_id": "bbbbbbbbbbbbbbbb", '
        '"parent_id": "aaaaaaaaaaaaaaaa", "t_perf": 1002.0, '
        '"dur_s": 0.1}' % EXT_TRACE,
        '{"event": "trace_span", "v": 1, "name": "torn-ta',  # torn tail
    ])
    merged = fleet.merge_shards(d)
    # The torn tail is skipped, not fatal; alignment puts A's record
    # (ts 2005) AFTER B's (ts 2002) despite the smaller raw t_perf.
    names = [r["name"] for r in merged if r["event"] == "trace_span"]
    assert names == ["early", "late"]
    aligns = [r["t_align"] for r in merged if r["event"] == "trace_span"]
    assert aligns == [2002.0, 2005.0]
    # Deterministic: two merges are record-identical and digest-equal.
    again = fleet.merge_shards(d)
    assert merged == again
    assert fleet.merge_digest(merged) == fleet.merge_digest(again)
    # Parent resolution spans shards.
    nodes = fleet.span_nodes(merged)
    assert nodes["bbbbbbbbbbbbbbbb"]["parent_id"] == "aaaaaaaaaaaaaaaa"
    assert "aaaaaaaaaaaaaaaa" in nodes


def test_assemble_grafts_coalesced_fan_in(tmp_path):
    # Request 2 coalesced into request 1's batch: the batch subtree
    # lives in trace-1, but request 2's assembled tree must include it
    # (grafted under its own root) plus the batch's descendants.
    d = str(tmp_path)
    t1, t2 = "1" * 32, "2" * 32
    r1, r2 = "a" * 16, "c" * 16
    batch, window = "b" * 16, "d" * 16
    _write_shard(d, "33.main.jsonl", [
        '{"event": "clock_anchor", "v": 1, "pid": 33, '
        '"shard": "33.main.jsonl", "perf_t": 0.0, "ts": 100.0}',
        # The batch fan-in node, owned by trace-1, naming both members.
        '{"event": "trace_span", "v": 1, "name": "serve_batch", '
        '"trace_id": "%s", "span_id": "%s", "parent_id": "%s", '
        '"t_perf": 1.0, "dur_s": 0.5, "fan_in": ["%s", "%s"]}'
        % (t1, batch, r1, r1, r2),
        # A window span under the batch (must graft too).
        '{"event": "trace_span", "v": 1, "name": "flight_span", '
        '"trace_id": "%s", "span_id": "%s", "parent_id": "%s", '
        '"t_perf": 1.1, "dur_s": 0.2}' % (t1, window, batch),
        '{"event": "request", "v": 1, "id": 1, "kind": "run-rounds", '
        '"status": "ok", "cohort": "c", "tenant": null, "wall_s": 0.5, '
        '"queue_s": 0.1, "coalesce_s": 0.1, "compile_s": 0.0, '
        '"dispatch_s": 0.2, "retire_lag_s": 0.1, '
        '"trace_id": "%s", "span_id": "%s"}' % (t1, r1),
        '{"event": "request", "v": 1, "id": 2, "kind": "run-rounds", '
        '"status": "ok", "cohort": "c", "tenant": null, "wall_s": 0.5, '
        '"queue_s": 0.1, "coalesce_s": 0.1, "compile_s": 0.0, '
        '"dispatch_s": 0.2, "retire_lag_s": 0.1, '
        '"trace_id": "%s", "span_id": "%s"}' % (t2, r2),
    ])
    merged = fleet.merge_shards(d)
    own = fleet.assemble_request_trace(merged, request_id=1)
    assert own["root_span"] == r1 and own["unparented"] == []
    assert {s["name"] for s in own["spans"]} == {
        "request", "serve_batch", "flight_span"
    }
    grafted = fleet.assemble_request_trace(merged, request_id=2)
    assert grafted["trace_id"] == t2 and grafted["root_span"] == r2
    # The foreign batch node AND its window descendant were grafted,
    # the batch reparented under request 2's own root.
    by_id = {s["span_id"]: s for s in grafted["spans"]}
    assert by_id[batch]["parent_id"] == r2
    assert by_id[window]["parent_id"] == batch
    assert grafted["unparented"] == []
    assert grafted["within_tol"] is True
    assert grafted["wall_s"] == pytest.approx(0.5)
    assert grafted["attribution_s"] == pytest.approx(0.5)


def test_assemble_shared_trace_excludes_siblings(tmp_path):
    # An external caller can inject the SAME traceparent into every
    # request of a batch: all members then share one trace id, and each
    # request's tree must contain its OWN subtree plus the grafted
    # batch — never a sibling's root (ownership, not trace id, decides
    # membership).
    d = str(tmp_path)
    t, ext = "e" * 32, "f" * 16
    r1, r2, batch = "1" * 16, "2" * 16, "3" * 16
    _write_shard(d, "44.main.jsonl", [
        '{"event": "clock_anchor", "v": 1, "pid": 44, '
        '"shard": "44.main.jsonl", "perf_t": 0.0, "ts": 100.0}',
        '{"event": "trace_span", "v": 1, "name": "serve_batch", '
        '"trace_id": "%s", "span_id": "%s", "parent_id": "%s", '
        '"t_perf": 1.0, "dur_s": 0.5, "fan_in": ["%s", "%s"]}'
        % (t, batch, r1, r1, r2),
        '{"event": "request", "v": 1, "id": 1, "kind": "run-rounds", '
        '"status": "ok", "cohort": "c", "tenant": null, "wall_s": 0.5, '
        '"queue_s": 0.1, "coalesce_s": 0.1, "compile_s": 0.0, '
        '"dispatch_s": 0.2, "retire_lag_s": 0.1, '
        '"trace_id": "%s", "span_id": "%s", "parent_id": "%s"}'
        % (t, r1, ext),
        '{"event": "request", "v": 1, "id": 2, "kind": "run-rounds", '
        '"status": "ok", "cohort": "c", "tenant": null, "wall_s": 0.5, '
        '"queue_s": 0.1, "coalesce_s": 0.1, "compile_s": 0.0, '
        '"dispatch_s": 0.2, "retire_lag_s": 0.1, '
        '"trace_id": "%s", "span_id": "%s", "parent_id": "%s"}'
        % (t, r2, ext),
    ])
    merged = fleet.merge_shards(d)
    for rid, root, sibling in ((1, r1, r2), (2, r2, r1)):
        tr = fleet.assemble_request_trace(merged, request_id=rid)
        ids = {s["span_id"] for s in tr["spans"]}
        assert root in ids and batch in ids and sibling not in ids
        assert tr["unparented"] == []
        # The non-owner's graft reparents the batch under ITS root.
        by_id = {s["span_id"]: s for s in tr["spans"]}
        assert by_id[batch]["parent_id"] == root


def test_committed_fixtures_assemble_fully_parented():
    merged = fleet.merge_shards(str(FIXTURES))
    assert len({r["shard"] for r in merged}) == 2  # main + pool worker
    rids = fleet.request_ids(merged)
    assert len(rids) == 3
    for rid in rids:
        tr = fleet.assemble_request_trace(merged, request_id=rid)
        assert tr["unparented"] == []
        assert tr["within_tol"] is True
    summary = fleet.fleet_summary(merged)
    assert summary["requests"] == 3 and summary["traces"] == 3
    assert summary["pool_tasks"] >= 1
    assert len(summary["replicas"]) == 2
    line = fleet.summary_line(summary)
    assert line.startswith("fleet replicas=2")


def test_fleet_cli_is_jax_free_subprocess():
    # The CI assembly stage depends on this: the module CLI must run
    # with jax unimportable, and its sentinel booleans must hold on the
    # committed fixtures.
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "from ba_tpu.obs import fleet\n"
        "sys.exit(fleet._main(['tests/fixtures/fleet']))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["merge_deterministic"] is True
    assert doc["all_spans_parented"] is True
    assert doc["critical_path_within_tol"] is True
    assert doc["request_traces"] == 3


def test_contracts_declare_fleet_families():
    from ba_tpu.analysis import contracts

    for fam, keys in (
        ("clock_anchor", ("pid", "shard", "perf_t", "ts")),
        ("trace_span", ("name", "trace_id", "span_id", "parent_id")),
        ("pool_task", ("kind", "rows", "wall_s", "t_perf")),
        ("request_trace", ("trace_id", "root_span", "spans",
                           "critical_path", "within_tol")),
        ("fleet_summary", ("replicas", "cohorts", "requests",
                           "pool_tasks", "traces")),
    ):
        spec = contracts.RECORD_FAMILIES[fam]
        assert set(keys) <= set(spec["required"]), fam
        # Not CI_REQUIRED: these families never appear on the MAIN
        # single-file wire — the dedicated sink-dir stage validates
        # them instead.
        assert not spec["ci"], fam
    assert "BA_TPU_TRACE_CONTEXT" in contracts.ENV_DOCUMENTED


# -- zero added sync: the no-blocking proof with fleet tracing live -----------


def test_supervised_mesh_no_blocking_with_fleet_tracing(
    eight_devices, monkeypatch, tmp_path
):
    # The ISSUE 19 schedule acceptance: trace propagation AND the
    # sharded sink live, on an 8-device forced-host mesh, under full
    # supervision — and the engine's only sync stays the depth-delayed
    # retire fetch (context stamping rides existing emits; it must add
    # ZERO new device syncs).
    import dataclasses

    import jax
    import jax.random as jr

    from ba_tpu.parallel import make_mesh, make_sweep_state
    from ba_tpu.runtime.supervisor import (
        SupervisorConfig, supervised_sweep,
    )
    from ba_tpu.scenario import compile_scenario, from_dict

    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    monkeypatch.setenv(trace.TRACE_CONTEXT_ENV, EXT_TP)
    d = str(tmp_path / "sink")
    os.makedirs(d)
    metrics.configure(d + os.sep)
    try:
        R, depth = 8, 3
        key = jr.key(91)
        state = make_sweep_state(jr.key(90), 16, 8, order=1)
        state = dataclasses.replace(
            state, faulty=state.faulty.at[:8, 0].set(True)
        )
        spec = from_dict({"name": "fleet-proof", "rounds": R,
                          "events": [{"round": 2, "kill": [1]}]})
        block = compile_scenario(spec, 16, 8, sparse=True)
        mesh = make_mesh((8, 1), ("data", "node"))
        events = []
        out = supervised_sweep(
            key, state, scenario=block, mesh=mesh,
            depth=depth, rounds_per_dispatch=1, health_every=2,
            checkpoint_every=4,
            checkpoint_path=str(tmp_path / "mesh_{round}.npz"),
            config=SupervisorConfig(timeout_s=60.0),
            on_event=lambda kind, i: events.append((kind, i)),
        )
        metrics.default_sink().close()
    finally:
        metrics.configure(None)
        metrics.set_run_id(None)
    # The schedule proof, unchanged with tracing live.
    dispatches = [i for kind, i in events if kind == "dispatch"]
    assert dispatches == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [
        ("dispatch", i) for i in range(depth + 1)
    ]
    assert out["stats"]["max_in_flight"] == depth + 1
    # Every record joined the external trace and the tree is parented
    # up to (exactly) the injected external span.
    merged = fleet.merge_shards(d)
    spans = [r for r in merged if r.get("event") == "flight_span"]
    assert len(spans) == R
    assert {r.get("trace_id") for r in merged if r.get("trace_id")} == {
        EXT_TRACE
    }
    nodes = fleet.span_nodes(merged)
    unresolved = {
        n["parent_id"] for n in nodes.values()
        if n["parent_id"] is not None and n["parent_id"] not in nodes
    }
    assert unresolved == {EXT_SPAN}
    assert fleet.merge_digest(merged) == fleet.merge_digest(
        fleet.merge_shards(d)
    )


# -- crash consistency: SIGKILL mid-flight, resume, tree stays parented -------


def test_kill_mid_flight_resume_keeps_tree_parented(tmp_path):
    # ISSUE 19 satellite: SIGKILL a TRACED campaign mid-flight (real
    # signal, subprocess) with the sharded sink live, auto-resume the
    # same call in this process, and the MERGED span tree stays
    # parented across the resume boundary — 100% of non-root spans
    # resolve a parent, one trace id, records from both pids.
    import dataclasses

    import jax.random as jr

    from ba_tpu.parallel import make_sweep_state
    from ba_tpu.parallel.pipeline import fresh_copy
    from ba_tpu.runtime.supervisor import (
        SupervisorConfig, supervised_sweep,
    )
    from ba_tpu.scenario import compile_scenario, from_dict

    R = 12
    d = str(tmp_path / "sink")
    os.makedirs(d)
    ck = tmp_path / "kill_{round}.npz"
    child = f'''
import dataclasses, jax.random as jr
from ba_tpu.parallel import make_sweep_state
from ba_tpu.runtime import chaos
from ba_tpu.runtime.supervisor import SupervisorConfig, supervised_sweep
from ba_tpu.scenario import compile_scenario, from_dict

key = jr.key(91)
state = make_sweep_state(jr.key(90), 16, 8, order=1)
state = dataclasses.replace(
    state, faulty=state.faulty.at[:8, 0].set(True)
)
spec = from_dict({{"name": "fleet-kill", "rounds": {R},
                  "events": [{{"round": 2, "kill": [1]}}]}})
block = compile_scenario(spec, 16, 8, sparse=True)
plan = chaos.from_dict({{
    "name": "mid-retire-kill",
    "faults": [{{"round": 10, "kind": "kill", "phase": "retire"}}],
}})
supervised_sweep(
    key, state, scenario=block, rounds_per_dispatch=2,
    checkpoint_every=4, checkpoint_path={str(ck)!r},
    chaos=plan, config=SupervisorConfig(timeout_s=60.0),
)
raise SystemExit("unreachable: the kill fault must have fired")
'''
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        BA_TPU_METRICS=d + os.sep,
        BA_TPU_TRACE_CONTEXT=EXT_TP,
        BA_TPU_COMPILE_LEDGER="0",
    )
    proc = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True, text=True, cwd=str(REPO), timeout=600,
        env=env,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    child_shards = {name for name, _ in fleet.list_shards(d)}
    assert len(child_shards) == 1
    # The successor: the SAME call in THIS process; the auto-resume
    # adopts the checkpoint header's traceparent, so its spans parent
    # under the child's pre-crash attempt span.
    key = jr.key(91)
    state = make_sweep_state(jr.key(90), 16, 8, order=1)
    state = dataclasses.replace(
        state, faulty=state.faulty.at[:8, 0].set(True)
    )
    spec = from_dict({"name": "fleet-kill", "rounds": R,
                      "events": [{"round": 2, "kill": [1]}]})
    block = compile_scenario(spec, 16, 8, sparse=True)
    metrics.configure(d + os.sep)
    try:
        supervised_sweep(
            key, fresh_copy(state), scenario=block, rounds_per_dispatch=2,
            checkpoint_every=4, checkpoint_path=str(ck),
            config=SupervisorConfig(timeout_s=60.0),
        )
        metrics.default_sink().close()
    finally:
        metrics.configure(None)
        metrics.set_run_id(None)
    merged = fleet.merge_shards(d)
    shards = {r["shard"] for r in merged}
    assert len(shards) == 2 and child_shards < shards
    # One trace across BOTH processes (the successor adopted the
    # checkpoint header's position, not a fresh root).
    assert {r.get("trace_id") for r in merged if r.get("trace_id")} == {
        EXT_TRACE
    }
    # 100% of non-root spans resolve a parent: the only id the stream
    # cannot resolve is the EXTERNAL injected span (the caller's — by
    # construction never in-stream).
    nodes = fleet.span_nodes(merged)
    unresolved = {
        n["parent_id"] for n in nodes.values()
        if n["parent_id"] is not None and n["parent_id"] not in nodes
    }
    assert unresolved == {EXT_SPAN}
    # Both processes contributed window spans to the one tree.
    span_shards = {
        r["shard"] for r in merged if r.get("event") == "flight_span"
    }
    assert len(span_shards) == 2
    # And the successor's attempt root parents under a span RECORDED by
    # the child (the resume-boundary edge the checkpoint header carried).
    attempts = [
        (r["shard"], r["span_id"], r["parent_id"]) for r in merged
        if r.get("event") == "trace_span"
        and r.get("name") == "supervised_attempt"
    ]
    assert len(attempts) == 2
    (child_shard, child_sid, child_par), (succ_shard, _, succ_par) = attempts
    assert child_shard != succ_shard
    assert child_par == EXT_SPAN
    assert succ_par == child_sid


# -- fatal paths flush the Chrome trace export --------------------------------


def test_supervisor_fatal_flushes_trace_export(tmp_path):
    # The export must be written BEFORE the fatal re-raises — the child
    # leaves via os._exit, so the atexit exporter never runs and the
    # file can only exist if the supervisor's flush wrote it.
    trace_path = tmp_path / "fatal_trace.json"
    child = '''
import os
import jax.random as jr
from ba_tpu.parallel import make_sweep_state
from ba_tpu.runtime import chaos
from ba_tpu.runtime.supervisor import (
    SupervisorConfig, SupervisorError, supervised_sweep,
)

plan = chaos.from_dict({
    "name": "fatal-now",
    "faults": [{"round": 0, "kind": "fatal"}],
})
try:
    # max_recoveries=0: the injected fatal immediately exhausts the
    # recovery budget -> the unrecoverable SupervisorError path (with
    # a budget left, a from-scratch restart would simply complete).
    supervised_sweep(
        jr.key(0), make_sweep_state(jr.key(1), 4, 4), 4,
        rounds_per_dispatch=2, chaos=plan,
        config=SupervisorConfig(timeout_s=60.0, backoff_base_s=0.0,
                                max_recoveries=0),
    )
except SupervisorError:
    os._exit(7)   # skip atexit: only the pre-raise flush can have run
os._exit(3)
'''
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        BA_TPU_TRACE=str(trace_path), BA_TPU_COMPILE_LEDGER="0",
    )
    proc = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True, text=True, cwd=str(REPO), timeout=600,
        env=env,
    )
    assert proc.returncode == 7, proc.stdout + proc.stderr
    assert trace_path.exists(), "fatal did not flush the trace export"
    doc = json.loads(trace_path.read_text())
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    assert events, "flushed trace export is empty"


# -- cross-process pool spans -------------------------------------------------


def test_pool_worker_writes_own_shard_and_parented_span(tmp_path):
    # The PROGRAMMATIC configure() path: no env var in play — _spawn
    # must forward the live sink's directory target to the worker.
    d = str(tmp_path / "sink")
    os.makedirs(d)
    d += os.sep
    metrics.configure(d)
    try:
        p = sign_pool.SignPool(1)
        try:
            assert p.workers == 1
            from ba_tpu.crypto.signed import verify_host_exact

            tp = metrics.format_traceparent("ab" * 16, "cd" * 8)
            pks = np.zeros((2, 32), np.uint8)
            msgs = np.zeros((2, 3, 8), np.uint8)
            sigs = np.zeros((2, 3, 64), np.uint8)
            verdicts = p.verify_rows(pks, msgs, sigs, traceparent=tp)
            # Bit-exact with the in-process host body (the pool's
            # correctness contract; the verdict VALUES are the crypto
            # backend's business, not this test's).
            np.testing.assert_array_equal(
                verdicts, verify_host_exact(pks, msgs, sigs)
            )
        finally:
            p.close()
    finally:
        metrics.configure(None)
    merged = fleet.merge_shards(d)
    # The worker opened its OWN shard (this process emitted nothing).
    worker_pids = {
        int(fleet.SHARD_RE.match(r["shard"]).group(1)) for r in merged
    }
    assert os.getpid() not in worker_pids and len(worker_pids) == 1
    tasks = [r for r in merged if r.get("event") == "pool_task"]
    assert len(tasks) == 1
    t = tasks[0]
    assert t["kind"] == "verify" and t["rows"] == 2
    assert isinstance(t["wall_s"], float) and isinstance(t["t_perf"], float)
    # The span parents under the piped staging position.
    assert t["trace_id"] == "ab" * 16
    assert t["parent_id"] == "cd" * 8
    assert len(t["span_id"]) == 16


# -- REPL fleet view ----------------------------------------------------------


def test_repl_stats_fleet_line(monkeypatch):
    from ba_tpu.runtime.backends import PyBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    cluster = Cluster(4, PyBackend(), seed=0)
    monkeypatch.delenv("BA_TPU_METRICS", raising=False)
    metrics.configure(None)
    out = []
    # No sharded sink: one explanatory line, no exception.
    handle_command(cluster, "stats --fleet", out.append)
    assert out and "no sharded sink" in out[0]
    # Sink routed at the committed fixtures (read-only: the sink opens
    # its shard lazily on first EMIT, and `stats --fleet` never emits).
    before = sorted(os.listdir(FIXTURES))
    metrics.configure(str(FIXTURES))
    try:
        out = []
        handle_command(cluster, "stats --fleet", out.append)
    finally:
        metrics.configure(None)
    assert sorted(os.listdir(FIXTURES)) == before
    assert len(out) == 1 and out[0].startswith("fleet replicas=2")
    assert "requests=3" in out[0] and "traces=3" in out[0]
