"""Fleet tier tests (ISSUE 20).

Two layers, mirroring the serving tests' split:

- the jax-free layer: hash-ring determinism and minimal-movement,
  fleet config validation + env parsing, campaign-spec doc round-trip,
  ledger folding, handoff header grammar, the serve-side ``handoff()``
  drain hook (re-homed tickets are NOT failures), the warm ring-entry
  gate, and the zero-in-flight drain no-op edge (no empty checkpoint
  or handoff files);
- the engine-backed layer: the kill-a-replica drill — a live campaign
  drained mid-flight resumes BIT-EXACTLY on a survivor, a SIGKILLed
  replica's orphans are adopted by ledgered fingerprint, forged
  handoff headers are refused (cross-protocol), and concurrent routed
  clients never hang through either event.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ba_tpu.fleet import (
    CampaignSpec,
    FleetConfig,
    FleetRouter,
    HandoffRefused,
    HashRing,
    ReplicaManager,
    read_handoff,
    read_ledger,
    verify_handoff,
    write_handoff,
)
from ba_tpu.fleet.router import _point
from ba_tpu.obs.registry import MetricsRegistry
from ba_tpu.runtime.serve import (
    AgreementRequest,
    AgreementService,
    ServeConfig,
    ServeError,
)


# -- jax-free layer -----------------------------------------------------------


def test_fleet_import_is_jax_free():
    # The BA301 host-tier contract, proven at runtime: a router host
    # needs no accelerator — importing the fleet tier (router, replica
    # state machine, migration verifier) must not pull jax.
    code = (
        "import sys; import ba_tpu.fleet; "
        "assert 'jax' not in sys.modules, 'fleet import pulled jax'; "
        "print('ok')"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "ok"


def test_hash_ring_deterministic_and_minimal_movement():
    # The vnode construction is content-addressed: pinned literally so
    # an accidental hash-grammar change (which would re-home every
    # cohort in a live fleet) fails a test, not a deployment.
    assert _point("replica-0", 0) == 17044263878877797094
    members = ["replica-0", "replica-1", "replica-2"]
    a = HashRing(members, vnodes=64)
    b = HashRing(reversed(members), vnodes=64)  # order-insensitive
    keys = [f"plain.r{r}.c4.xla.m1" for r in (1, 2, 4, 8, 16, 32)]
    for k in keys:
        order = a.prefer(k)
        assert order == b.prefer(k)
        assert sorted(order) == sorted(members)  # every member once
    # Minimal movement: removing one member only re-homes the cohorts
    # whose hash home WAS that member; everyone else keeps theirs.
    gone = "replica-1"
    small = HashRing([m for m in members if m != gone], vnodes=64)
    for k in keys:
        before = a.prefer(k)[0]
        after = small.prefer(k)[0]
        if before != gone:
            assert after == before
    assert HashRing((), vnodes=64).prefer("anything") == []
    with pytest.raises(ValueError):
        HashRing(members, vnodes=0)


def test_fleet_config_validate_and_env(monkeypatch):
    with pytest.raises(ValueError):
        FleetConfig(replicas=0)
    with pytest.raises(ValueError):
        FleetConfig(max_hops=0)
    with pytest.raises(ValueError):
        FleetConfig(vnodes=0)
    with pytest.raises(ValueError):
        FleetConfig(replicas=4, max_replicas=2)
    monkeypatch.setenv("BA_TPU_FLEET_REPLICAS", "3")
    monkeypatch.setenv("BA_TPU_FLEET_HOPS", "2")
    monkeypatch.setenv("BA_TPU_FLEET_VNODES", "16")
    monkeypatch.setenv("BA_TPU_FLEET_ROOT", "/tmp/fleet-env-test")
    cfg = FleetConfig.from_env()
    assert (cfg.replicas, cfg.max_hops, cfg.vnodes) == (3, 2, 16)
    assert cfg.root == "/tmp/fleet-env-test"
    # Explicit overrides beat the environment.
    assert FleetConfig.from_env(replicas=5).replicas == 5
    monkeypatch.setenv("BA_TPU_FLEET_REPLICAS", "lots")
    with pytest.raises(ValueError):
        FleetConfig.from_env()


def test_campaign_spec_doc_roundtrip_and_validation():
    spec = CampaignSpec(
        campaign="c1", seed=11, state_seed=12, batch=8, rounds=64
    )
    doc = spec.to_doc()
    assert "scenario" not in doc  # None scenario drops from the doc
    assert CampaignSpec.from_doc(doc) == spec
    assert json.loads(json.dumps(doc)) == doc  # JSON-clean
    with pytest.raises(ValueError):
        CampaignSpec.from_doc({**doc, "surprise": 1})
    with pytest.raises(ValueError):
        CampaignSpec.from_doc("not a dict")
    # The id becomes a directory under the fleet root: path-unsafe
    # names are refused eagerly.
    for bad in ("", "a/b", "..", "x\x00y"):
        with pytest.raises(ValueError):
            CampaignSpec(
                campaign=bad, seed=1, state_seed=2, batch=4, rounds=8
            )
    with pytest.raises(ValueError):
        CampaignSpec(
            campaign="c", seed=1, state_seed=2, batch=0, rounds=8
        )


def test_read_ledger_folds_statuses(tmp_path):
    root = str(tmp_path)
    path = os.path.join(root, "replicas", "replica-0", "ledger.jsonl")
    os.makedirs(os.path.dirname(path))
    rows = [
        {"ev": "admit", "campaign": "done1", "doc": {"d": 1},
         "template": "t1"},
        {"ev": "checkpoint", "campaign": "done1", "fingerprint": "fp1"},
        {"ev": "done", "campaign": "done1"},
        {"ev": "admit", "campaign": "handed", "doc": {"d": 2},
         "template": "t2"},
        {"ev": "handoff", "campaign": "handed"},
        {"ev": "admit", "campaign": "orphan", "doc": {"d": 3},
         "template": "t3"},
        {"ev": "checkpoint", "campaign": "orphan", "fingerprint": "fp3"},
    ]
    with open(path, "w", encoding="utf-8") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
        f.write('{"ev": "checkpoint", "campaign": "orp')  # torn tail
    by_id = {e["campaign"]: e for e in read_ledger(root, "replica-0")}
    assert by_id["done1"]["status"] == "done"
    assert by_id["handed"]["status"] == "handoff"
    assert by_id["orphan"]["status"] == "orphaned"
    assert by_id["orphan"]["fingerprint"] == "fp3"
    assert by_id["orphan"]["template"] == "t3"
    assert read_ledger(root, "never-wrote") == []


def test_handoff_header_grammar(tmp_path):
    path = str(tmp_path / "handoff.json")
    header = write_handoff(
        path,
        campaign="c1",
        doc={"campaign": "c1"},
        template=str(tmp_path / "ck_{round}.npz"),
        round_cursor=32,
        rounds=64,
        checkpoint=str(tmp_path / "ck_32.npz"),
        fingerprint="fp",
        signed=False,
        from_replica="replica-0",
    )
    assert read_handoff(path) == header
    # Malformed headers are refused loudly, never half-parsed.
    bad = str(tmp_path / "bad.json")
    with open(bad, "w", encoding="utf-8") as f:
        f.write("not json")
    with pytest.raises(HandoffRefused):
        read_handoff(bad)
    with pytest.raises(HandoffRefused):
        read_handoff(str(tmp_path / "missing.json"))
    for mutate in (
        {"format": "other"},
        {"v": 99},
    ):
        with open(bad, "w", encoding="utf-8") as f:
            json.dump({**header, **mutate}, f)
        with pytest.raises(HandoffRefused):
            read_handoff(bad)
    incomplete = dict(header)
    del incomplete["fingerprint"]
    with open(bad, "w", encoding="utf-8") as f:
        json.dump(incomplete, f)
    with pytest.raises(HandoffRefused, match="fingerprint"):
        read_handoff(bad)
    # verify_handoff: a header pointing at a checkpoint that does not
    # validate is refused before any engine work.
    with pytest.raises(HandoffRefused, match="failed validation"):
        verify_handoff(header)


def test_serve_handoff_rehomes_without_counting_failures():
    # The drain hook's contract: queued-but-never-dispatched tickets
    # fail with a re-homable ServeError (so no caller hangs) but are
    # NOT counted as failures and emit NO terminal request record — a
    # drain is a move, not an outcome.
    reg = MetricsRegistry()
    svc = AgreementService(
        ServeConfig(max_queue=8, warm=False), registry=reg
    )
    svc.open()  # admission only: no dispatcher, the queue just fills
    tickets = [
        svc.submit(
            AgreementRequest(kind="run-rounds", n=4, seed=i, rounds=2),
            deadline_s=None,
        )
        for i in range(3)
    ]
    rehomed = svc.handoff()
    assert [t.id for t in rehomed] == [t.id for t in tickets]
    for t in tickets:
        with pytest.raises(ServeError, match="re-homed"):
            t.result(timeout=1.0)
    assert svc.stats()["failed"] == 0
    assert reg.counter("serve_failed_total").value == 0
    with pytest.raises(ServeError):
        svc.submit(AgreementRequest(kind="run-rounds", rounds=2))


def test_warm_ok_is_the_ring_entry_gate():
    from ba_tpu.runtime.warmup import WarmupRunner

    runner = WarmupRunner(None, [], registry=MetricsRegistry())
    assert not runner.ok()  # never ran: not warm
    runner._done.set()
    assert runner.ok()
    runner.errors = 1  # a failed signature → never enters the ring
    assert not runner.ok()


def _admission_only_fleet(serve_config, replicas=2, **cfg):
    """A manager whose replicas accept but never dispatch (no
    dispatcher thread, no jax): admission-layer routing tests."""
    mgr = ReplicaManager(
        FleetConfig(replicas=replicas, **cfg), serve_config=serve_config
    )
    for _ in range(replicas):
        rep = mgr._new_replica()
        rep.service.open()
        rep.set_state("ready")
    return mgr


def test_router_routes_by_cohort_and_bounds_hops():
    mgr = _admission_only_fleet(ServeConfig(max_queue=4, warm=False))
    router = FleetRouter(mgr)
    req = AgreementRequest(kind="run-rounds", n=4, seed=1, rounds=2)
    t = router.submit(req, deadline_s=None)
    assert t.admit_hops == 1 and t.reroutes == 0
    # Same cohort → same replica, every time (coalescing locality).
    names = {router.submit(req, deadline_s=None).replica
             for _ in range(3)}
    assert names == {t.replica}
    # Empty fleet: a plain ServeError, not a hang.
    empty = ReplicaManager(FleetConfig(replicas=1))
    with pytest.raises(ServeError, match="no ready replica"):
        FleetRouter(empty).submit(req, deadline_s=None)
    stats = router.stats()
    assert stats["routes"] == 4 and stats["ready"] == 2


def test_router_hops_off_overloaded_home_replica():
    # The hash home sheds → the request lands on the next ring member
    # instead of bouncing back to the client.
    mgr = _admission_only_fleet(ServeConfig(max_queue=8, warm=False))
    router = FleetRouter(mgr)
    req = AgreementRequest(kind="run-rounds", n=4, seed=1, rounds=2)
    home = router.submit(req, deadline_s=None).replica
    mgr.get(home).service._tier = 3  # shed_all on the home replica
    routed = router.submit(req, deadline_s=None)
    assert routed.replica != home
    assert routed.admit_hops == 2


def test_routed_ticket_rehomes_off_a_draining_replica():
    # "Never a hung client", deterministically: a ticket queued on the
    # home replica when its serve-side handoff fires is transparently
    # re-submitted on the survivor inside the caller's result() budget
    # (no dispatcher anywhere, so the re-homed ticket then times out —
    # proving the reroute happened and the budget still bounds it).
    mgr = _admission_only_fleet(ServeConfig(max_queue=8, warm=False))
    router = FleetRouter(mgr)
    req = AgreementRequest(kind="run-rounds", n=4, seed=1, rounds=2)
    routed = router.submit(req, deadline_s=None)
    home = routed.replica
    mgr.get(home).service.handoff()
    mgr.get(home).set_state("stopped")
    with pytest.raises(TimeoutError):
        routed.result(timeout=0.5)
    assert routed.reroutes == 1
    assert routed.replica != home
    assert routed.tried == [home, routed.replica]
    assert router.stats()["reroutes"] == 1
    # And when the LAST replica dies too: a loud ServeError, no hang.
    survivor = routed.replica
    mgr.get(survivor).service.handoff()
    mgr.get(survivor).set_state("stopped")
    with pytest.raises(ServeError, match="no surviving replica"):
        routed.result(timeout=5.0)


def test_drain_zero_campaigns_is_strict_noop(tmp_path):
    # The no-op edge the issue pins: draining a replica with zero
    # in-flight campaigns must not litter the fleet root with empty
    # handoff or checkpoint state someone later mistakes for a
    # campaign.
    root = str(tmp_path / "fleet")
    mgr = ReplicaManager(
        FleetConfig(replicas=2, root=root),
        serve_config=ServeConfig(warm=False),
    )
    mgr.start()
    assert [r.state for r in mgr.all()] == ["ready", "ready"]
    adopted = mgr.drain("replica-0")
    assert adopted == []
    assert mgr.get("replica-0").state == "stopped"
    assert not os.path.exists(os.path.join(root, "campaigns"))
    leftover = [
        os.path.join(dirpath, f)
        for dirpath, _, files in os.walk(root)
        for f in files
        if f != "ledger.jsonl"
    ]
    assert leftover == []
    # The survivor still serves; the drained replica left the ring.
    router = FleetRouter(mgr)
    assert router.stats()["ready"] == 1
    mgr.stop()


def test_repl_fleet_command(tmp_path):
    # The REPL surface (jax-free on the PyBackend roster): start /
    # stat / drain / stop plus the one-line error grammar.
    from ba_tpu.runtime.backends import PyBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    cluster = Cluster(4, PyBackend(), seed=0)
    lines = []
    assert handle_command(cluster, "fleet", lines.append)
    assert lines[-1].startswith("fleet error: usage:")
    handle_command(cluster, "fleet stat", lines.append)
    assert lines[-1] == "fleet error: not running (fleet start first)"
    handle_command(cluster, "fleet start replicas=two", lines.append)
    assert lines[-1] == "fleet error: replicas= wants a int, got 'two'"
    handle_command(cluster, "fleet start replicas=0", lines.append)
    assert lines[-1].startswith("fleet error: replicas=0")
    root = str(tmp_path / "fleet")
    handle_command(
        cluster, f"fleet start replicas=2 root={root} queue=4",
        lines.append,
    )
    assert lines[-1].startswith("fleet: started 2 replica(s)")
    handle_command(cluster, "fleet start replicas=1", lines.append)
    assert lines[-1] == "fleet error: already running (fleet stop first)"
    lines.clear()
    handle_command(cluster, "fleet stat", lines.append)
    assert lines[0] == "fleet_routes 0"
    assert sum(1 for ln in lines if ln.startswith("fleet_replica ")) == 2
    handle_command(cluster, "fleet drain nope", lines.append)
    assert lines[-1].startswith("fleet error:")
    handle_command(cluster, "fleet drain replica-0", lines.append)
    assert lines[-1] == (
        "fleet: drained replica-0 — 0 campaign(s) migrated, "
        "1 replica(s) still serving"
    )
    handle_command(cluster, "fleet stop", lines.append)
    assert lines[-1] == "fleet: stopped — routes=0, reroutes=0"
    assert cluster._fleet_manager is None


# -- engine-backed fleet drill ------------------------------------------------


def _spawn_clients(router, n, seed0):
    """n concurrent routed clients; returns (threads, results dict)."""
    results = {}

    def client(i):
        t = router.submit(
            AgreementRequest(
                kind="run-rounds", n=4, seed=seed0 + i, rounds=2
            ),
            deadline_s=None,
        )
        results[i] = t.result(timeout=120)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(n)
    ]
    for t in threads:
        t.start()
    return threads, results


def _join_all(threads):
    for t in threads:
        t.join(120)
    return sum(t.is_alive() for t in threads)


def test_fleet_drill_drain_resume_and_kill_adopt(tmp_path):
    # THE acceptance drill (ISSUE 20), both failure modes in one fleet:
    # (1) serve-drain a replica mid-campaign under concurrent routed
    #     load → zero hung clients, the campaign resumes BIT-EXACTLY on
    #     the survivor, and a forged handoff header is refused;
    # (2) SIGKILL a replica mid-campaign → zero hung clients, its
    #     orphan is adopted by ledgered fingerprint, bit-exactly.
    import jax.random as jr

    from ba_tpu.parallel import make_sweep_state
    from ba_tpu.runtime.supervisor import (
        SupervisorConfig,
        supervised_sweep,
    )

    rounds = 4000
    want = supervised_sweep(
        jr.key(11),
        make_sweep_state(jr.key(12), 8, 4),
        rounds,
        rounds_per_dispatch=1,
        collect_decisions=True,
        config=SupervisorConfig(timeout_s=60.0),
    )

    root = str(tmp_path / "fleet")
    mgr = ReplicaManager(
        FleetConfig(replicas=2, root=root),
        serve_config=ServeConfig(
            max_queue=16, coalesce_window_s=0.01, warm=False
        ),
    )
    mgr.start()
    router = FleetRouter(mgr)

    def start_campaign(replica, cid):
        # Same seeds both phases: one reference covers both (the
        # fingerprint is seed-derived, not campaign-id-derived).
        handle = mgr.get(replica).run_campaign(CampaignSpec(
            campaign=cid, seed=11, state_seed=12, batch=8,
            rounds=rounds, capacity=4, checkpoint_every=8,
        ))
        deadline = time.perf_counter() + 60
        while handle.fingerprint is None and not handle.done():
            assert time.perf_counter() < deadline, "no first checkpoint"
            time.sleep(0.02)
        return handle

    # -- phase 1: serve-drain under load --------------------------------------
    h1 = start_campaign("replica-1", "c1")
    threads, results = _spawn_clients(router, 8, seed0=0)
    adopted = mgr.drain("replica-1")
    assert h1.outcome == "handoff", (h1.outcome, h1.error)
    header = read_handoff(h1.handoff_path)
    verify_handoff(header)
    forged = {**header, "signed": not header["signed"]}
    with pytest.raises(HandoffRefused, match="cross-protocol"):
        verify_handoff(forged)
    with pytest.raises(HandoffRefused, match="fingerprint"):
        verify_handoff({**header, "fingerprint": "0" * 64})
    assert _join_all(threads) == 0, "hung client through drain"
    assert len(results) == 8
    assert all(isinstance(r, dict) for r in results.values())
    (h2,) = adopted
    assert h2.wait(240) and h2.outcome == "completed", (
        h2.outcome, h2.error,
    )
    np.testing.assert_array_equal(
        h2.result["decisions"], want["decisions"]
    )
    np.testing.assert_array_equal(
        h2.result["histograms"], want["histograms"]
    )
    # history_start == 0: resume reassembled the FULL history (carry +
    # rows sidecar), not a truncated suffix.
    assert h2.result["supervisor"]["history_start"] == 0

    # -- phase 2: kill + orphan adoption --------------------------------------
    mgr.start_replica()  # the survivor ("replica-2")
    h3 = start_campaign("replica-0", "c2")
    threads2, results2 = _spawn_clients(router, 8, seed0=100)
    mgr.kill("replica-0")
    assert h3.wait(120) and h3.outcome == "abandoned", h3.outcome
    # A SIGKILLed lane writes nothing terminal: no handoff file, and
    # its ledger entry folds to "orphaned".
    assert h3.handoff_path is None
    statuses = {
        e["campaign"]: e["status"]
        for e in read_ledger(root, "replica-0")
    }
    assert statuses["c2"] == "orphaned"
    assert _join_all(threads2) == 0, "hung client through kill"
    assert all(isinstance(r, dict) for r in results2.values())
    (h4,) = mgr.adopt_orphans("replica-0")
    assert h4.wait(240) and h4.outcome == "completed", (
        h4.outcome, h4.error,
    )
    np.testing.assert_array_equal(
        h4.result["decisions"], want["decisions"]
    )
    assert h4.result["supervisor"]["history_start"] == 0

    assert router.stats()["routes"] == 16
    mgr.stop()
