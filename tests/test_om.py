"""OM(1) properties: validity, agreement, fault model (ba.py:159-285)."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from ba_tpu.core import ATTACK, RETREAT, UNDEFINED, make_state, om1_agreement, om1_round


def test_no_faults_everyone_agrees():
    state = make_state(8, 4, order=ATTACK)
    out = om1_agreement(jr.key(0), state)
    assert np.all(np.asarray(out["majorities"]) == ATTACK)
    assert np.all(np.asarray(out["decision"]) == ATTACK)
    assert np.all(np.asarray(out["needed"]) == 3)
    assert np.all(np.asarray(out["total"]) == 4)


def test_one_faulty_lieutenant_validity():
    # n=4, 1 traitor lieutenant (BASELINE config #1): every honest lieutenant
    # tallies own order + 1 honest + 1 coin -> order always wins 2-1 or 3-0.
    faulty = jnp.zeros((64, 4), bool).at[:, 2].set(True)
    state = make_state(64, 4, order=ATTACK, faulty=faulty)
    for seed in range(5):
        maj = np.asarray(om1_round(jr.key(seed), state))
        assert np.all(maj[:, 0] == ATTACK)  # leader: own command (Q1)
        assert np.all(maj[:, 1] == ATTACK)
        assert np.all(maj[:, 3] == ATTACK)


def test_faulty_leader_agreement():
    # IC1: with only the leader faulty, all honest lieutenants compute the
    # same majority (they all see the same round-2 answer multiset).
    faulty = jnp.zeros((128, 4), bool).at[:, 0].set(True)
    state = make_state(128, 4, order=ATTACK, faulty=faulty)
    for seed in range(5):
        maj = np.asarray(om1_round(jr.key(seed), state))
        lieutenants = maj[:, 1:]
        assert np.all(lieutenants == lieutenants[:, :1])
    # Q1: the faulty leader still reports its true command as its majority.
    assert np.all(maj[:, 0] == ATTACK)


def test_faulty_leader_equivocates():
    # A faulty leader's round-1 messages differ across recipients in some
    # instances — the equivocation of ba.py:268-273.
    from ba_tpu.core.om import round1_broadcast

    faulty = jnp.zeros((256, 8), bool).at[:, 0].set(True)
    state = make_state(256, 8, order=ATTACK, faulty=faulty)
    received = np.asarray(round1_broadcast(jr.key(3), state))
    lieutenants = received[:, 1:]
    per_instance_varies = (lieutenants != lieutenants[:, :1]).any(axis=1)
    assert per_instance_varies.any()
    # Leader's own slot is always the true order (ba.py:261).
    assert np.all(received[:, 0] == ATTACK)


def test_dead_nodes_do_not_vote():
    # Kill node 3 of 4: lieutenants tally own + 1 peer (leader skipped, dead
    # skipped) -> still unanimous on the order.
    alive = jnp.ones((4, 4), bool).at[:, 3].set(False)
    state = make_state(4, 4, order=RETREAT, alive=alive)
    out = om1_agreement(jr.key(1), state)
    assert np.all(np.asarray(out["total"]) == 3)
    assert np.all(np.asarray(out["needed"]) == 2)
    assert np.all(np.asarray(out["decision"]) == RETREAT)


def test_two_node_quorum_override():
    # n=2: the lieutenant has only its own vote -> majority = received order;
    # total=2 -> needed=1 (Q7: a single general can win a 2-node quorum).
    state = make_state(1, 2, order=ATTACK)
    out = om1_agreement(jr.key(0), state)
    assert np.asarray(out["majorities"]).tolist() == [[ATTACK, ATTACK]]
    assert int(out["needed"][0]) == 1
    assert int(out["decision"][0]) == ATTACK


def test_tie_gives_undefined_majority():
    # n=3, faulty lieutenant: honest lieutenant tallies own order + the
    # traitor's coin -> exact tie (UNDEFINED, ba.py:188-195) whenever the
    # coin disagrees with the order. Over many instances both outcomes occur.
    faulty = jnp.zeros((512, 3), bool).at[:, 2].set(True)
    state = make_state(512, 3, order=ATTACK, faulty=faulty)
    maj = np.asarray(om1_round(jr.key(11), state))[:, 1]
    assert set(maj.tolist()) == {ATTACK, UNDEFINED}


def test_all_dead_cluster_undecided():
    # A fully-killed cluster must not fabricate a consensus (the reference
    # crashes before this state is reachable, SURVEY.md Q4).
    alive = jnp.zeros((1, 3), bool)
    out = om1_agreement(jr.key(0), make_state(1, 3, order=ATTACK, alive=alive))
    assert int(out["total"][0]) == 0
    assert int(out["decision"][0]) == UNDEFINED


def test_jit_compiles_once():
    state = make_state(16, 8, order=ATTACK)
    fn = jax.jit(om1_agreement)
    out1 = fn(jr.key(0), state)
    out2 = fn(jr.key(1), state)
    assert out1["majorities"].shape == (16, 8)
    assert out2["decision"].shape == (16,)


def test_nonleader_leader_index():
    # Leader need not be index 0 (post-election clusters, ba.py:126-157).
    state = make_state(8, 5, order=ATTACK, leader=2)
    maj = np.asarray(om1_round(jr.key(0), state))
    assert np.all(maj == ATTACK)
