"""Adversary search engine tests (ISSUE 15, ``ba_tpu/search/``).

The contracts under pin:

- generator determinism: same seed -> identical population, and the
  population lowering confines candidate i's events to instance i;
- eager validation: hand-edited search configs fail with
  ScenarioError-grade messages before any array is built;
- objective scoring reads EXACTLY what the engine's per-slot counter
  blocks carry: the quorum column matches an independent host
  derivation from the decisions stream, and every slot's block is
  bit-identical to the same candidate's standalone B=1 run (the
  serving parity pin as the search's correctness oracle);
- the end-to-end acceptance: a CI-sized seeded hunt finds an IC
  violation from a random population, ddmin-shrinks it, and the shrunk
  spec replayed standalone reproduces the violation bit-exactly;
- search-state checkpoints resume a hunt bit-exactly mid-hunt;
- the depth-k no-blocking dispatch-count proof re-runs with the search
  harness live;
- the ``python -m ba_tpu.search`` corpus CLI is jax-free (subprocess
  pin), and the COMMITTED ``examples/scenarios/found/`` reproducers
  replay their provenance counters bit-for-bit.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ba_tpu.scenario.spec import ScenarioError, from_dict, to_dict
from ba_tpu.search.generate import (
    SearchSpace,
    campaign_fingerprint,
    lower_population,
    mutate_campaign,
    sample_campaign,
    sample_population,
    space_from_dict,
    space_to_dict,
    validate_space,
)
from ba_tpu.search.objective import (
    OBJECTIVES,
    counters_dict,
    get_objective,
    score_rows,
    violation_rows,
)
from ba_tpu.utils.snapshot import (
    read_search_checkpoint,
    write_search_checkpoint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One shared shape for the engine-touching tests: every hunt below
# compiles the same coalesced megastep specializations, so the suite
# pays the trace once (the persistent-cache discipline).
SPACE = SearchSpace(
    rounds=4, capacity=6, population=8, events_min=2, events_max=5
)


def _hunt(**kwargs):
    from ba_tpu.search.loop import hunt

    return hunt(**kwargs)


# -- jax-free layers ---------------------------------------------------------


def test_space_validation_eager():
    with pytest.raises(ScenarioError, match="population"):
        validate_space(SearchSpace(rounds=4, capacity=6, population=0))
    with pytest.raises(ScenarioError, match="events_min"):
        validate_space(
            SearchSpace(
                rounds=4, capacity=6, population=4,
                events_min=5, events_max=2,
            )
        )
    with pytest.raises(ScenarioError, match="strategies"):
        validate_space(
            SearchSpace(
                rounds=4, capacity=6, population=4,
                strategies=("nope",),
            )
        )
    with pytest.raises(ScenarioError, match="kinds"):
        validate_space(
            SearchSpace(rounds=4, capacity=6, population=4, kinds=())
        )
    with pytest.raises(ScenarioError, match="faulty_max"):
        validate_space(
            SearchSpace(rounds=4, capacity=6, population=4, faulty_max=99)
        )
    with pytest.raises(ScenarioError, match="ids_per_event"):
        validate_space(
            SearchSpace(
                rounds=4, capacity=6, population=4, ids_per_event=7
            )
        )
    with pytest.raises(ScenarioError, match="order"):
        validate_space(
            SearchSpace(rounds=4, capacity=6, population=4, order="march")
        )
    # And the objective table is eager too.
    with pytest.raises(ScenarioError, match="unknown search objective"):
        get_objective("win")


def test_space_doc_round_trip_and_unknown_keys():
    doc = space_to_dict(SPACE)
    assert space_to_dict(space_from_dict(json.loads(json.dumps(doc)))) == doc
    with pytest.raises(ScenarioError, match="unknown search space"):
        space_from_dict({**doc, "zap": 1})
    with pytest.raises(ScenarioError, match="missing"):
        space_from_dict({"rounds": 4})


def test_generator_determinism_and_budgets():
    pop1 = sample_population(SPACE, seed=11)
    pop2 = sample_population(SPACE, seed=11)
    assert [to_dict(c) for c in pop1] == [to_dict(c) for c in pop2]
    assert len(pop1) == SPACE.population
    # A different seed diverges (overwhelmingly; pinned for this seed
    # pair so the test is deterministic).
    pop3 = sample_population(SPACE, seed=12)
    assert [to_dict(c) for c in pop1] != [to_dict(c) for c in pop3]
    # Budgets hold on every sample, including under tight caps.
    tight = SearchSpace(
        rounds=4, capacity=6, population=16,
        events_min=2, events_max=5, faulty_max=1, kill_max=2,
        kinds=("kill", "set_faulty", "set_strategy"),
    )
    for c in sample_population(tight, seed=5):
        assert len(c.events) <= tight.events_max
        made_faulty = {
            g for ev in c.events
            if ev.kind == "set_faulty" and ev.value for g in ev.ids
        }
        killed = {
            g for ev in c.events if ev.kind == "kill" for g in ev.ids
        }
        assert len(made_faulty) <= 1
        assert len(killed) <= 2
    # Revive-enabled spaces sample clean too: the kill branch excludes
    # same-round revived generals (and vice versa), so the
    # validates-by-construction contract holds for the full kind menu
    # (regression: revive-then-kill of one general in one round used to
    # raise ScenarioError from inside sample_campaign, aborting hunts).
    from ba_tpu.scenario.spec import EVENT_KINDS

    flap = SearchSpace(
        rounds=2, capacity=4, population=4,
        events_min=4, events_max=8, kinds=EVENT_KINDS,
    )
    for uid in range(300):
        sample_campaign(flap, 0, uid)
    # Mutation is deterministic per (seed, uid) and validates.
    parent = pop1[0]
    m1 = mutate_campaign(parent, SPACE, 11, 500)
    m2 = mutate_campaign(parent, SPACE, 11, 500)
    assert to_dict(m1) == to_dict(m2)
    assert m1.name == "search-s11-u500"


def test_lower_population_confines_events_to_instances():
    pop = sample_population(SPACE, seed=11)
    block = lower_population(pop, SPACE.capacity, SPACE.rounds)
    assert block.batch == len(pop)
    planes = block.chunk(0, SPACE.rounds)
    from ba_tpu.scenario.compile import compile_scenario

    for i, campaign in enumerate(pop):
        single = compile_scenario(
            campaign, batch=1, capacity=SPACE.capacity
        )
        np.testing.assert_array_equal(planes["kill"][:, i], single.kill[:, 0])
        np.testing.assert_array_equal(
            planes["set_faulty"][:, i], single.set_faulty[:, 0]
        )
        np.testing.assert_array_equal(
            planes["set_strategy"][:, i], single.set_strategy[:, 0]
        )
    # Rows outside a candidate's instance never carry its events: sum
    # of per-candidate mutated cells equals the population's.
    assert (planes["set_faulty"] >= 0).sum() == sum(
        (
            compile_scenario(c, batch=1, capacity=SPACE.capacity)
            .set_faulty >= 0
        ).sum()
        for c in pop
    )


def test_objective_scores_and_errors():
    names = ("quorum_failures", "unanimous_rounds",
             "equivocation_observed", "ic1_violations", "ic2_violations")
    rows = np.array([[0, 4, 0, 0, 0], [2, 4, 1, 3, 1]], np.int32)
    assert list(score_rows(rows, names, "ic")) == [0, 4]
    assert list(score_rows(rows, names, "havoc")) == [0, 8 * 3 + 8 + 4 + 1]
    assert list(violation_rows(rows, names, "ic")) == [False, True]
    assert list(violation_rows(rows, names, "quorum")) == [False, True]
    assert counters_dict(rows[1], names)["ic1_violations"] == 3
    with pytest.raises(ScenarioError, match="not in the run's table"):
        score_rows(rows, ("a", "b", "c", "d", "e"), "ic")
    with pytest.raises(ScenarioError, match="expected"):
        score_rows(rows[0], names, "ic")
    assert set(OBJECTIVES) == {"ic1", "ic2", "ic", "quorum", "havoc"}


def test_search_checkpoint_schema_rejects_corruption(tmp_path):
    path = str(tmp_path / "hunt.json")
    write_search_checkpoint(path, {"seed": 1}, run_id="run-abc")
    meta, state = read_search_checkpoint(path)
    assert state == {"seed": 1}
    assert meta["run_id"] == "run-abc"
    assert meta["format"] == "ba_tpu.search_state"
    doc = json.load(open(path))
    doc["state"]["seed"] = 2  # tamper: digest must catch it
    open(path, "w").write(json.dumps(doc))
    with pytest.raises(ValueError, match="digest mismatch"):
        read_search_checkpoint(path)
    open(path, "w").write("{not json")
    with pytest.raises(ValueError, match="not valid JSON"):
        read_search_checkpoint(path)
    open(path, "w").write('{"format": "other", "v": 1}')
    with pytest.raises(ValueError, match="format"):
        read_search_checkpoint(path)


def test_cli_corpus_is_jax_free_subprocess():
    # The BA301 host-tier contract, proven at runtime on the REAL
    # committed corpus: the corpus/sample subcommands must never pull
    # jax (CI runs them on accelerator-free hosts).
    code = (
        "import sys; from ba_tpu.search.__main__ import main; "
        "rc = main(['corpus', 'examples/scenarios/found']); "
        "assert 'jax' not in sys.modules, 'search CLI pulled jax'; "
        "sys.exit(rc)"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert "corpus OK" in out.stdout


def test_export_refuses_non_bit_exact(tmp_path):
    from ba_tpu.search.corpus import export_found

    entry = {
        "doc": to_dict(sample_campaign(SPACE, 11, 0)),
        "uid": 0,
        "generation": 0,
        "score": 1,
        "counters": {"ic1_violations": 1},
        "bit_exact": False,
    }
    with pytest.raises(ScenarioError, match="parity oracle"):
        export_found(
            [entry], str(tmp_path), seed=11, objective="ic",
            capacity=SPACE.capacity,
        )


# -- engine-touching contracts ------------------------------------------------


def test_objective_vs_host_derivation_and_alone_parity():
    # A seeded population KNOWN to contain violating campaigns
    # (seed 3 over this space is the schema-check hunt's sweep).
    from ba_tpu.core import UNDEFINED
    from ba_tpu.search.loop import (
        candidate_keys,
        evaluate_alone,
        evaluate_population,
        population_state,
    )

    pop = sample_population(SPACE, seed=3)
    uids = list(range(len(pop)))
    block = lower_population(pop, SPACE.capacity, SPACE.rounds)
    res = evaluate_population(
        candidate_keys(3, uids),
        population_state(len(pop), SPACE.capacity, SPACE.order),
        block,
        rounds=SPACE.rounds,
    )
    rows = res["counters"]
    names = res["counter_names"]
    scores = score_rows(rows, names, "ic")
    violations = violation_rows(rows, names, "ic")
    assert violations.any(), "seeded sweep lost its violating campaigns"
    # Host derivation: the per-slot quorum_failures column is exactly
    # the count of UNDEFINED decisions in that slot's stream.
    q = list(names).index("quorum_failures")
    np.testing.assert_array_equal(
        rows[:, q], (res["decisions"] == UNDEFINED).sum(axis=0)
    )
    # The parity oracle: every slot's counter block (and decision /
    # leader stream) is bit-identical to the candidate's own B=1 run.
    for i in np.flatnonzero(violations)[:2]:
        alone = evaluate_alone(
            pop[i], seed=3, uid=uids[i], capacity=SPACE.capacity
        )
        np.testing.assert_array_equal(alone["counters"], rows[i])
        np.testing.assert_array_equal(
            alone["decisions"], res["decisions"][:, i]
        )
        np.testing.assert_array_equal(
            alone["leaders"], res["leaders"][:, i]
        )
        assert int(scores[i]) >= 1


def test_hunt_end_to_end_finds_shrinks_and_reproduces(tmp_path):
    # ISSUE 15 acceptance: a CI-sized seeded hunt finds at least one
    # IC-violating campaign from a random population, shrinks it, and
    # the shrunk spec replayed STANDALONE reproduces the violation
    # bit-exactly (decisions/leaders/counters — the oracle inside
    # verify_minimized, re-checked here independently).
    from ba_tpu.search.loop import evaluate_alone

    res = _hunt(
        space=SPACE, seed=3, generations=2, objective="ic",
        minimize=True, minimize_max=2,
        export_dir=str(tmp_path / "found"),
    )
    assert res["stats"]["found"] >= 1
    assert res["minimized"], "hunt found nothing to minimize"
    for m in res["minimized"]:
        assert m["bit_exact"] is True
        assert m["events_after"] <= m["events_before"]
        assert m["score"] >= 1
        shrunk = from_dict(m["doc"])
        alone = evaluate_alone(
            shrunk, seed=3, uid=m["uid"], capacity=SPACE.capacity
        )
        got = counters_dict(alone["counters"], alone["counter_names"])
        assert got == m["counters"]
        assert violation_rows(
            np.asarray(alone["counters"])[None, :],
            alone["counter_names"], "ic",
        )[0]
    # The export landed as ordinary provenance-stamped spec files that
    # the corpus contract accepts.
    from ba_tpu.search.corpus import load_corpus

    specs = load_corpus(str(tmp_path / "found"))
    assert len(specs) == len(res["minimized"])
    assert all(
        s.provenance["search"]["capacity"] == SPACE.capacity for s in specs
    )
    # Dedup: every found entry is a distinct campaign.
    fps = [
        campaign_fingerprint(from_dict(e["doc"])) for e in res["found"]
    ]
    assert len(fps) == len(set(fps))


def test_hunt_checkpoint_resume_bit_exact(tmp_path):
    ck = str(tmp_path / "hunt_g{generation}.json")
    full = _hunt(
        space=SPACE, seed=3, generations=3, objective="ic",
        minimize=True, minimize_max=1, checkpoint_path=ck,
    )
    assert full["stats"]["checkpoints"] == 3
    resumed = _hunt(
        resume=str(tmp_path / "hunt_g1.json"), generations=3,
        minimize=True, minimize_max=1,
    )
    # The resumed hunt's findings, elites and final state are
    # bit-identical to the uninterrupted run's — and it joined the
    # same flight ledger (run_id inherited from the checkpoint).
    assert resumed["found"] == full["found"]
    assert resumed["elites"] == full["elites"]
    assert resumed["minimized"] == full["minimized"]
    assert resumed["state"] == full["state"]
    assert resumed["stats"]["run_id"] == full["stats"]["run_id"]
    # A conflicting space is refused loudly.
    other = SearchSpace(rounds=4, capacity=6, population=4)
    with pytest.raises(ScenarioError, match="different search space"):
        _hunt(
            space=other, resume=str(tmp_path / "hunt_g1.json"),
            generations=3,
        )
    # A completed hunt's checkpoint needs a larger generations=.
    with pytest.raises(ScenarioError, match="outside hunt"):
        _hunt(resume=str(tmp_path / "hunt_g3.json"), generations=3)


def test_hunt_eager_validation():
    with pytest.raises(ScenarioError, match="generations"):
        _hunt(space=SPACE, generations=0)
    with pytest.raises(ScenarioError, match="checkpoint_path"):
        _hunt(space=SPACE, checkpoint_every=2)
    with pytest.raises(ScenarioError, match="needs a search space"):
        _hunt()
    with pytest.raises(ScenarioError, match="unknown search objective"):
        _hunt(space=SPACE, objective="win")
    # Population/shard divisibility fails BEFORE any evaluation.
    import jax

    with pytest.raises(ScenarioError, match="does not divide"):
        _hunt(
            space=SearchSpace(rounds=4, capacity=6, population=5),
            mesh=jax.devices()[:2],
        )


def test_search_depth_k_no_blocking_with_harness_live(monkeypatch):
    # The dispatch-count proof, re-run with the search harness live:
    # one population evaluation keeps depth+1 dispatches in flight and
    # never calls block_until_ready — phases observed through the
    # engine's execution seam.
    import jax

    from ba_tpu.search.loop import (
        candidate_keys,
        evaluate_population,
        population_state,
    )

    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the search")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    events = []

    def seam(call, phase, d, lo, hi):
        events.append((phase, d))
        return call()

    rounds, depth = 7, 3
    space = SearchSpace(
        rounds=rounds, capacity=SPACE.capacity, population=8,
        events_min=2, events_max=5,
    )
    pop = sample_population(space, seed=3)
    block = lower_population(pop, space.capacity, rounds)
    evaluate_population(
        candidate_keys(3, list(range(8))),
        population_state(8, space.capacity, space.order),
        block,
        rounds=rounds, depth=depth, rounds_per_dispatch=1,
        exec_seam=seam,
    )
    dispatches = [d for p, d in events if p == "dispatch"]
    retires = [d for p, d in events if p == "retire"]
    assert dispatches == list(range(rounds))
    assert retires == list(range(rounds))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [
        ("dispatch", i) for i in range(depth + 1)
    ]
    for r in range(rounds - depth):
        assert events.index(("retire", r)) > events.index(
            ("dispatch", r + depth)
        )


def test_mesh_sharded_hunt_bit_exact(eight_devices):
    # Per-shard populations (mesh=): shard assignment is layout only —
    # per-slot keys make every candidate's stream placement-free, so a
    # 2-device hunt is bit-exact with the single-device hunt.
    from ba_tpu.parallel import make_mesh

    plain = _hunt(
        space=SPACE, seed=3, generations=2, objective="ic",
        minimize=False,
    )
    sharded = _hunt(
        space=SPACE, seed=3, generations=2, objective="ic",
        minimize=False, mesh=make_mesh((2, 1), ("data", "node")),
    )
    assert sharded["found"] == plain["found"]
    assert sharded["elites"] == plain["elites"]
    assert sharded["state"] == plain["state"]
    assert sharded["stats"]["shards"] == 2


def test_committed_reproducers_replay_bit_exact():
    # Satellite pin: the COMMITTED examples/scenarios/found corpus —
    # the specs the search engine discovered — replays its provenance
    # counters bit-for-bit from (seed, uid, capacity) alone, and every
    # spec still violates its recorded objective.
    from ba_tpu.search.corpus import load_corpus
    from ba_tpu.search.loop import evaluate_alone

    specs = load_corpus(os.path.join(REPO, "examples", "scenarios", "found"))
    assert len(specs) >= 2
    for spec in specs:
        pr = spec.provenance["search"]
        alone = evaluate_alone(
            spec, seed=pr["seed"], uid=pr["uid"], capacity=pr["capacity"]
        )
        got = counters_dict(alone["counters"], alone["counter_names"])
        assert got == pr["counters"], spec.name
        assert violation_rows(
            np.asarray(alone["counters"])[None, :],
            alone["counter_names"], pr["objective"],
        )[0], spec.name


def test_cluster_run_search_and_repl_smoke():
    from ba_tpu.runtime.backends import JaxBackend, PyBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    cluster = Cluster(4, JaxBackend(), seed=0)
    res = cluster.run_search(
        space=SPACE, generations=1, objective="ic", minimize=False,
    )
    assert res is not None
    assert res["stats"]["campaigns"] == SPACE.population
    # The roster is untouched: the hunt runs from the canonical state.
    assert len(cluster.generals) == 4
    # REPL surface: output lines + one-line errors, no tracebacks.
    lines = []
    handle_command(
        cluster, "search gens=1 objective=quorum", lines.append
    )
    assert any(line.startswith("Search:") for line in lines)
    assert any(line.startswith("Search found:") for line in lines)
    errs = []
    handle_command(cluster, "search gens=zero", errs.append)
    assert errs and errs[0].startswith("search error:")
    errs2 = []
    handle_command(cluster, "search objective=win", errs2.append)
    assert errs2 and "unknown search objective" in errs2[0]
    # Incapable backends stay silent, like scenario.
    quiet = []
    py_cluster = Cluster(4, PyBackend(), seed=0)
    handle_command(py_cluster, "search gens=1", quiet.append)
    assert quiet == []
