"""Worker process for the two-process ``jax.distributed`` integration test.

Launched by tests/test_multihost.py as ``python tests/multihost_worker.py
<process_id> <num_processes> <coordinator_port> <out_json>``.  Each worker
pins itself to the CPU platform with 4 virtual devices, joins the
multi-process runtime through ``ba_tpu.parallel.multihost.init_distributed``
(the framework analogue of the reference's join protocol, ba.py:86-102),
builds the global (data, node) mesh — exercising ``make_global_mesh``'s
multi-host branch, which a single process can never reach — and runs the
node-sharded SM round plus the sharded sweep.  Process 0 writes the
replicated/gathered results as JSON for the test to compare against the
single-process 8-device run (both form a (4, 2) mesh, so every per-shard
PRNG fold is identical and results must match bit-for-bit).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    port, out_path = sys.argv[3], sys.argv[4]

    # Platform pinning must precede the first backend query; see
    # ba_tpu/utils/platform.py for why this is in-process config, not env.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import jax.random as jr
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from ba_tpu.parallel.multihost import (
        init_distributed,
        make_global_mesh,
        put_global,
    )

    got = init_distributed(f"localhost:{port}", nproc, pid)
    assert got == nproc, f"expected {nproc} processes, runtime says {got}"
    assert jax.process_index() == pid

    mesh = make_global_mesh(node_devices_per_host=2)
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 2 * nproc,
        "node": 2,
    }

    from ba_tpu.core import ATTACK, make_state
    from ba_tpu.parallel.eig_parallel import eig_node_sharded
    from ba_tpu.parallel.node_parallel import om1_node_sharded
    from ba_tpu.parallel.sm_parallel import sm_node_sharded
    from ba_tpu.parallel.sweep import make_sweep_state, sharded_sweep

    # -- node-sharded SM(2), collapsed relay, pinned round-1 values --------
    B, n = 16, 8
    faulty = np.zeros((B, n), bool)
    faulty[:, 3] = True
    local_state = make_state(B, n, order=ATTACK, faulty=faulty)
    state = jax.tree.map(
        lambda x: put_global(mesh, x, P("data", *([None] * (x.ndim - 1)))),
        local_state,
    )
    # Round 1 is pinned host-side: its eager path draws from a local typed
    # key, which cannot cross a multi-process mesh.
    received = np.full((B, n), int(ATTACK), np.int8)
    out_sm = sm_node_sharded(
        mesh,
        jr.key(7),
        state,
        2,
        received=put_global(mesh, received, P("data", None)),
        collapsed=True,
    )
    dec_sm = np.asarray(
        multihost_utils.process_allgather(out_sm["decision"], tiled=True)
    )
    # Default round-1 path (received=None): runs under jit so the global
    # state arrays are legal inputs even on a multi-process mesh.
    out_sm2 = sm_node_sharded(mesh, jr.key(10), state, 2, collapsed=True)
    dec_sm2 = np.asarray(
        multihost_utils.process_allgather(out_sm2["decision"], tiled=True)
    )

    # -- node-sharded OM(1) and EIG on the same global mesh ----------------
    out_om = om1_node_sharded(mesh, jr.key(11), state)
    dec_om = np.asarray(
        multihost_utils.process_allgather(out_om["decision"], tiled=True)
    )
    out_eig = eig_node_sharded(mesh, jr.key(12), state, 2)
    dec_eig = np.asarray(
        multihost_utils.process_allgather(out_eig["decision"], tiled=True)
    )

    # -- sharded sweep over the global mesh --------------------------------
    sweep_state = make_sweep_state(jr.key(8), 32, 16)
    out_sw = sharded_sweep(mesh, jr.key(9), sweep_state)
    hist = np.asarray(out_sw["histogram"])  # replicated output
    dec_sw = np.asarray(
        multihost_utils.process_allgather(out_sw["decision"], tiled=True)
    )

    if pid == 0:
        with open(out_path, "w") as f:
            json.dump(
                {
                    "sm_decision": dec_sm.tolist(),
                    "sm_default_r1_decision": dec_sm2.tolist(),
                    "om1_decision": dec_om.tolist(),
                    "eig_decision": dec_eig.tolist(),
                    "sweep_decision": dec_sw.tolist(),
                    "sweep_histogram": hist.tolist(),
                },
                f,
            )
    multihost_utils.sync_global_devices("ba_tpu multihost worker done")


if __name__ == "__main__":
    main()
