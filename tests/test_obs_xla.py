"""Device-tier observability tests (ISSUE 4 tentpole, ba_tpu/obs/xla.py
+ the recompile explainer in obs/instrument.py).

Contracts pinned here:

1. **Artifact introspection on CPU**: `obs.xla.introspect` AOT-compiles
   a jitted callable and emits one versioned ``compiled_artifact``
   record with nonzero flops/bytes and — for a donating program —
   nonzero ``alias_bytes`` (the donate_argnums contract made visible),
   plus registry gauges and the HLO dump when ``BA_TPU_HLO`` is set.
2. **Pipeline wiring**: a ``pipeline_sweep`` run with the sink live
   emits exactly one artifact per specialization, whose alias bytes
   cover the donated state+schedule bytes.
3. **Recompile explainer**: a seen function compiling again emits
   exactly ONE ``recompile`` record naming exactly the changed axis —
   through the raw classifier, and end-to-end through ``JaxBackend``'s
   capacity re-specialization.
4. **Disabled = free**: with no ``BA_TPU_*`` set the introspector never
   runs (no records, no extra compiles) and ``annotate`` degrades to a
   nullcontext without importing the profiler.
"""

import contextlib
import json

import pytest

from ba_tpu import obs
from ba_tpu.obs.registry import MetricsRegistry
from ba_tpu.obs.trace import Tracer
from ba_tpu.utils import metrics


@pytest.fixture
def fresh_obs(monkeypatch, tmp_path):
    """Fresh tracer/registry/instrument state + a live sink in tmp_path;
    yields the sink path."""
    monkeypatch.delenv("BA_TPU_HLO", raising=False)
    monkeypatch.delenv("BA_TPU_XPROF", raising=False)
    monkeypatch.setattr(obs.trace, "_default", Tracer(enabled=True))
    monkeypatch.setattr(obs.registry, "_default", MetricsRegistry())
    path = tmp_path / "metrics.jsonl"
    monkeypatch.setattr(metrics, "_default", metrics.MetricsSink(str(path)))
    obs.reset_first_calls()
    yield path
    metrics.default_sink().close()
    obs.reset_first_calls()


def _records(path, event=None):
    if not path.exists():  # lazily-opened sink that never emitted
        return []
    recs = [
        json.loads(line)
        for line in path.read_text().splitlines()
        if line.strip()
    ]
    if event is not None:
        recs = [r for r in recs if r["event"] == event]
    return recs


# -- 1. introspection ---------------------------------------------------------


def test_introspect_emits_versioned_artifact_with_alias(fresh_obs):
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x, y: (x @ y, x + 1), donate_argnums=(0,))
    x = jnp.ones((16, 16))
    y = jnp.ones((16, 16))
    rec = obs.xla.introspect(f, "toy_matmul", (x, y), axes={"n": 16})
    assert rec is not None
    metrics.default_sink().close()
    (on_disk,) = _records(fresh_obs, "compiled_artifact")
    assert on_disk["v"] == 1 and on_disk["fn"] == "toy_matmul"
    assert on_disk["axes"] == {"n": 16}
    assert on_disk["flops"] > 0
    assert on_disk["bytes_accessed"] > 0
    # x (16*16 f32) is donated and comes back as an output: XLA aliases
    # exactly its bytes.  This is the donation-evidence contract.
    assert on_disk["alias_bytes"] == 16 * 16 * 4
    assert on_disk["donation_aliased"] is True
    # Gauges mirror the record for scrape-style consumers.
    snap = obs.default_registry().snapshot()
    assert snap["xla_toy_matmul_flops"]["value"] == on_disk["flops"]
    assert snap["xla_toy_matmul_alias_bytes"]["value"] == 16 * 16 * 4
    # The harvest cost is itself observable.
    assert snap["xla_introspect_s"]["count"] == 1


def test_introspect_hlo_dump(fresh_obs, monkeypatch, tmp_path):
    import jax
    import jax.numpy as jnp

    hlo = tmp_path / "hlo"
    monkeypatch.setenv("BA_TPU_HLO", str(hlo))
    f = jax.jit(lambda x: x * 2)
    rec = obs.xla.introspect(f, "doubler", (jnp.ones((8,)),), axes={"n": 8})
    assert rec["hlo_dump"] is not None
    dumps = sorted(p.name for p in hlo.iterdir())
    assert any(n.startswith("doubler-") and n.endswith(".stablehlo.txt")
               for n in dumps)
    text = next(
        p for p in hlo.iterdir() if p.name.endswith(".stablehlo.txt")
    ).read_text()
    assert "stablehlo" in text or "mhlo" in text or "func" in text


def test_pipeline_sweep_emits_one_artifact_per_specialization(fresh_obs):
    import jax.random as jr

    from ba_tpu.parallel import make_sweep_state, pipeline_sweep

    state = make_sweep_state(jr.key(61), 8, 8)
    out = pipeline_sweep(
        jr.key(62), state, 4, depth=2, rounds_per_dispatch=2,
        with_counters=True,
    )
    assert out["stats"]["dispatches"] == 2
    metrics.default_sink().close()
    arts = _records(fresh_obs, "compiled_artifact")
    # One specialization (no ragged remainder) -> exactly one artifact.
    assert len(arts) == 1 and arts[0]["fn"] == "pipeline_megastep"
    assert arts[0]["flops"] > 0 and arts[0]["bytes_accessed"] > 0
    # Donation evidence: the aliased bytes cover the whole donated
    # carry — SimState planes + KeySchedule (key data + counter).
    import jax

    donated = sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves((state,))
    )
    assert arts[0]["alias_bytes"] >= donated > 0
    assert arts[0]["axes"]["capacity"] == 8
    assert arts[0]["axes"]["counters"] is True


def test_introspection_failure_is_nonfatal(fresh_obs, capsys):
    rec = obs.xla.introspect(object(), "not_jitted", (), axes={})
    assert rec is None
    assert "introspection of 'not_jitted' failed" in capsys.readouterr().err
    metrics.default_sink().close()
    assert _records(fresh_obs, "compiled_artifact") == []


# -- 2. recompile explainer ---------------------------------------------------


def test_recompile_record_names_changed_axis_exactly_once(fresh_obs):
    with obs.compile_or_dispatch_span("fnx", axes={"capacity": 4, "m": 1}) as p:
        assert p == "compile"  # first ever: compile, but nothing to diff
    with obs.compile_or_dispatch_span("fnx", axes={"capacity": 4, "m": 1}) as p:
        assert p == "dispatch"  # cached: no record
    with obs.compile_or_dispatch_span("fnx", axes={"capacity": 8, "m": 1}) as p:
        assert p == "compile"  # re-specialization: THE recompile
    with obs.compile_or_dispatch_span("fnx", axes={"capacity": 8, "m": 1}) as p:
        assert p == "dispatch"  # cached again: still one record
    metrics.default_sink().close()
    recs = _records(fresh_obs, "recompile")
    assert len(recs) == 1
    assert recs[0]["fn"] == "fnx"
    assert recs[0]["changed"] == {"capacity": [4, 8]}  # m unchanged: absent
    assert recs[0]["axes"] == {"capacity": 8, "m": 1}
    # The instant marker and counter ride along.
    names = [e["name"] for e in obs.default_tracer().chrome_events()
             if e["ph"] == "i"]
    assert names.count("recompile") == 1
    snap = obs.default_registry().snapshot()
    assert snap["recompiles_total"]["value"] == 1


def test_backend_capacity_recompile_is_attributed(fresh_obs):
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import General

    backend = JaxBackend(platform="cpu")
    generals = [General(id=i + 1, port=0) for i in range(4)]
    backend.run_round(generals, 0, 1, seed=0)  # capacity 4: first compile
    backend.run_round(generals, 0, 1, seed=1)  # cached dispatch
    generals.append(General(id=5, port=0))
    backend.run_round(generals, 0, 1, seed=2)  # capacity 8: recompile
    metrics.default_sink().close()
    recs = _records(fresh_obs, "recompile")
    assert len(recs) == 1 and recs[0]["fn"] == "jax_backend_step"
    assert recs[0]["changed"] == {"capacity": [4, 8]}
    # The interactive step's artifacts rode along, one per capacity.
    arts = _records(fresh_obs, "compiled_artifact")
    caps = sorted(a["axes"]["capacity"] for a in arts
                  if a["fn"] == "jax_backend_step")
    assert caps == [4, 8]


def test_mesh_device_count_recompile_is_attributed(fresh_obs, eight_devices):
    # ISSUE 8 satellite: the mesh data-axis SIZE rides the engine's
    # named-axes signature, so moving a sweep from one device to an 8x1
    # mesh at EQUAL shapes reads as `"data": [1, 8]` in the recompile
    # record instead of an unexplained re-specialization.  (The same
    # axes feed the cross-run compile ledger's signatures.)
    import jax.random as jr

    from ba_tpu.parallel import make_mesh, make_sweep_state, pipeline_sweep
    from ba_tpu.parallel.pipeline import fresh_copy

    state = make_sweep_state(jr.key(1), 16, 8)
    pipeline_sweep(jr.key(0), fresh_copy(state), 2, rounds_per_dispatch=2)
    mesh = make_mesh((8, 1), ("data", "node"))
    pipeline_sweep(
        jr.key(0), state, 2, rounds_per_dispatch=2, mesh=mesh
    )
    metrics.default_sink().close()
    recs = [
        r for r in _records(fresh_obs, "recompile")
        if r["fn"] == "pipeline_megastep"
    ]
    assert len(recs) == 1
    assert recs[0]["changed"] == {"data": [1, 8]}
    assert recs[0]["axes"]["data"] == 8


# -- 2b. cross-run recompile ledger (ISSUE 6) ---------------------------------


def test_ledger_explains_first_compile_of_the_session(fresh_obs, tmp_path):
    # "Process 1": compile fnl under jax_version A; the ledger persists
    # the merged signature.  "Process 2" (reset + reconfigure): the
    # FIRST compile of the session diffs against process 1 and emits a
    # cross_process recompile record naming the env axis — the row the
    # in-process explainer could never produce.
    ledger = tmp_path / "axes_ledger.json"
    obs.configure_compile_ledger(str(ledger), {"jax_version": "0.4.1"})
    try:
        with obs.compile_or_dispatch_span(
            "fnl", axes={"capacity": 4}
        ) as p:
            assert p == "compile"
        metrics.default_sink().close()
        assert _records(fresh_obs, "recompile") == []  # nothing to diff
        doc = json.loads(ledger.read_text())
        assert doc["fns"]["fnl"] == [
            {"capacity": 4, "jax_version": "0.4.1"}
        ]

        obs.reset_first_calls()  # "new process"
        obs.configure_compile_ledger(str(ledger), {"jax_version": "0.5.0"})
        with obs.compile_or_dispatch_span(
            "fnl", axes={"capacity": 4}
        ) as p:
            assert p == "compile"
        metrics.default_sink().close()
        (rec,) = _records(fresh_obs, "recompile")
        assert rec["fn"] == "fnl"
        assert rec["cross_process"] is True
        assert rec["changed"] == {"jax_version": ["0.4.1", "0.5.0"]}
        assert obs.default_registry().snapshot()[
            "recompiles_total"
        ]["value"] == 1
    finally:
        obs.configure_compile_ledger(None)


def test_ledger_silent_on_identical_signature_and_unknown_fn(
    fresh_obs, tmp_path
):
    ledger = tmp_path / "axes_ledger.json"
    obs.configure_compile_ledger(str(ledger), {"jax_version": "A"})
    try:
        with obs.compile_or_dispatch_span("fns", axes={"m": 1}):
            pass
        obs.reset_first_calls()  # same toolchain, same axes: silent
        obs.configure_compile_ledger(str(ledger), {"jax_version": "A"})
        with obs.compile_or_dispatch_span("fns", axes={"m": 1}) as p:
            assert p == "compile"
        # A fn with no prior row is a plain first compile, no record.
        with obs.compile_or_dispatch_span("fresh_fn", axes={"m": 2}) as p:
            assert p == "compile"
        metrics.default_sink().close()
        assert _records(fresh_obs, "recompile") == []
        # In-process re-specialization still reports cross_process=False.
        with obs.compile_or_dispatch_span("fns", axes={"m": 3}):
            pass
        metrics.default_sink().close()
        (rec,) = _records(fresh_obs, "recompile")
        assert rec["cross_process"] is False
        assert rec["changed"] == {"m": [1, 3]}
        # The ledger's write-through kept every specialization compiled
        # this session, in compile order.
        doc = json.loads(ledger.read_text())
        assert [s["m"] for s in doc["fns"]["fns"]] == [1, 3]
        assert [s["m"] for s in doc["fns"]["fresh_fn"]] == [2]
    finally:
        obs.configure_compile_ledger(None)


def test_ledger_corrupt_file_starts_fresh(fresh_obs, tmp_path):
    ledger = tmp_path / "axes_ledger.json"
    ledger.write_text("{not json")
    obs.configure_compile_ledger(str(ledger), {})
    try:
        with obs.compile_or_dispatch_span("fnc", axes={"n": 1}) as p:
            assert p == "compile"
        metrics.default_sink().close()
        assert _records(fresh_obs, "recompile") == []
        assert json.loads(ledger.read_text())["fns"]["fnc"] == [{"n": 1}]
    finally:
        obs.configure_compile_ledger(None)


def test_ledger_remembers_every_specialization(fresh_obs, tmp_path):
    # A fn that legitimately compiles at several signatures every session
    # (backends.py's capacity re-specialization) must NOT read as a
    # cross-process change when the next process replays the same set —
    # only a genuinely new signature does.
    ledger = tmp_path / "axes_ledger.json"
    obs.configure_compile_ledger(str(ledger), {"jax_version": "A"})
    try:
        with obs.compile_or_dispatch_span("fnm", axes={"capacity": 4}):
            pass
        with obs.compile_or_dispatch_span("fnm", axes={"capacity": 8}):
            pass
        metrics.default_sink().close()
        # The in-process 4 -> 8 re-specialization is the only record.
        (rec,) = _records(fresh_obs, "recompile")
        assert rec["cross_process"] is False

        obs.reset_first_calls()  # "new process", identical workload
        obs.configure_compile_ledger(str(ledger), {"jax_version": "A"})
        with obs.compile_or_dispatch_span("fnm", axes={"capacity": 4}) as p:
            assert p == "compile"  # session-first, but ledger-known
        metrics.default_sink().close()
        recs = _records(fresh_obs, "recompile")
        assert not [r for r in recs if r.get("cross_process")]
        # Dying after replaying only capacity=4 must not shrink the
        # ledger: a third session whose FIRST compile is a signature
        # neither prior process ever had still gets the cross-process
        # diff, against the most recent prior specialization.
        obs.reset_first_calls()
        obs.configure_compile_ledger(str(ledger), {"jax_version": "A"})
        with obs.compile_or_dispatch_span("fnm", axes={"capacity": 16}):
            pass
        metrics.default_sink().close()
        cross = [r for r in _records(fresh_obs, "recompile")
                 if r.get("cross_process")]
        assert [r["changed"] for r in cross] == [{"capacity": [8, 16]}]
    finally:
        obs.configure_compile_ledger(None)


def test_ledger_diffs_against_closest_prior_signature(fresh_obs, tmp_path):
    # A fn the previous process compiled at capacities 4 AND 8 that
    # recompiles at capacity 4 after a toolchain bump must read as
    # "jax_version changed" alone — diffing against the most recent
    # prior row (capacity 8) would also name capacity, an axis that
    # forced nothing.
    ledger = tmp_path / "axes_ledger.json"
    obs.configure_compile_ledger(str(ledger), {"jax_version": "old"})
    try:
        for cap in (4, 8):
            with obs.compile_or_dispatch_span("fnc", axes={"capacity": cap}):
                pass
        obs.reset_first_calls()
        obs.configure_compile_ledger(str(ledger), {"jax_version": "new"})
        with obs.compile_or_dispatch_span("fnc", axes={"capacity": 4}):
            pass
        metrics.default_sink().close()
        cross = [r for r in _records(fresh_obs, "recompile")
                 if r.get("cross_process")]
        assert [r["changed"] for r in cross] == [
            {"jax_version": ["old", "new"]}
        ]
    finally:
        obs.configure_compile_ledger(None)


def test_ledger_merges_concurrent_writer_rows(fresh_obs, tmp_path):
    # Two processes share one cache dir (the default outside the test
    # suite) and each rewrites the whole file.  A row another process
    # stored AFTER this process read its configure-time snapshot must
    # survive this process's next write — otherwise the next session
    # reads the erased row as a spurious cross-process recompile.
    ledger = tmp_path / "axes_ledger.json"
    obs.configure_compile_ledger(str(ledger), {"jax_version": "A"})
    try:
        other_sig = {"capacity": 16, "jax_version": "A"}
        ledger.write_text(
            json.dumps({"v": 1, "fns": {"other_fn": [other_sig]}})
        )
        with obs.compile_or_dispatch_span("mine", axes={"capacity": 4}):
            pass
        doc = json.loads(ledger.read_text())
        assert doc["fns"]["other_fn"] == [other_sig]
        assert {"capacity": 4, "jax_version": "A"} in doc["fns"]["mine"]
    finally:
        obs.configure_compile_ledger(None)


def test_enable_compilation_cache_configures_ledger(monkeypatch, tmp_path):
    # The wiring contract: a live persistent cache places the ledger
    # NEXT TO it with jax/jaxlib env axes, and BA_TPU_COMPILE_LEDGER=0
    # (what conftest sets suite-wide) keeps it off.
    from ba_tpu.obs import instrument
    from ba_tpu.utils.platform import enable_compilation_cache

    monkeypatch.setenv("BA_TPU_COMPILE_CACHE", str(tmp_path / "xla"))
    monkeypatch.setenv("BA_TPU_COMPILE_LEDGER", "1")
    try:
        path = enable_compilation_cache()
        assert path == str(tmp_path / "xla")
        assert instrument._ledger_path == str(
            tmp_path / "xla" / "ba_tpu_axes_ledger.json"
        )
        import jax

        assert instrument._ledger_env["jax_version"] == jax.__version__
        assert "jaxlib_version" in instrument._ledger_env
        monkeypatch.setenv("BA_TPU_COMPILE_LEDGER", "0")
        enable_compilation_cache()
        assert instrument._ledger_path is None
    finally:
        # Restore the suite's shared cache dir + ledger-off hygiene.
        monkeypatch.delenv("BA_TPU_COMPILE_CACHE")
        monkeypatch.setenv("BA_TPU_COMPILE_LEDGER", "0")
        enable_compilation_cache()
        obs.configure_compile_ledger(None)


# -- 3. disabled path ---------------------------------------------------------


def test_disabled_path_no_records_no_introspection(monkeypatch, tmp_path):
    import jax.random as jr

    from ba_tpu.parallel import make_sweep_state, pipeline_sweep

    for var in ("BA_TPU_METRICS", "BA_TPU_TRACE", "BA_TPU_HLO",
                "BA_TPU_XPROF"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(obs.trace, "_default", Tracer(enabled=False))
    monkeypatch.setattr(obs.registry, "_default", MetricsRegistry())
    monkeypatch.setattr(metrics, "_default", metrics.MetricsSink())
    monkeypatch.chdir(tmp_path)
    assert not obs.xla.enabled()

    calls = []
    monkeypatch.setattr(
        obs.xla, "introspect",
        lambda *a, **k: calls.append(a) or None,
    )
    obs.reset_first_calls()
    state = make_sweep_state(jr.key(63), 8, 8)
    out = pipeline_sweep(jr.key(64), state, 4, depth=2, with_counters=True)
    assert out["stats"]["dispatches"] == 4
    assert out["counters"].keys() == {
        "quorum_failures", "unanimous_rounds", "equivocation_observed"
    }
    assert calls == []  # gated out before the (expensive) AOT compile
    assert list(tmp_path.iterdir()) == []  # zero file writes
    assert len(obs.default_tracer()) == 0


def test_annotate_inactive_is_free_nullcontext(monkeypatch):
    monkeypatch.delenv("BA_TPU_XPROF", raising=False)
    cm = obs.xla.annotate("megastep_dispatch", dispatch=0)
    assert isinstance(cm, contextlib.nullcontext)
    with cm:
        pass
