"""Observability: structured JSON-lines metrics from the agreement round.

SURVEY.md section 6: the reference has print()-only observability
(ba.py:255,389); the framework must do far better.  These pin the metrics
contract: one parseable line per ``actual-order``, with decision, vote
counts, quorum threshold, fault count, and wall time — and zero lines
(plus unchanged REPL output) when the sink is disabled.
"""

import json

from ba_tpu.runtime.backends import PyBackend
from ba_tpu.runtime.cluster import Cluster
from ba_tpu.utils import metrics


def _with_sink(monkeypatch, target):
    monkeypatch.setattr(metrics, "_default", metrics.MetricsSink(target))


def test_round_emits_one_json_line(tmp_path, monkeypatch):
    path = tmp_path / "metrics.jsonl"
    _with_sink(monkeypatch, str(path))
    cluster = Cluster(4, PyBackend(), seed=0)
    cluster.set_faulty(2, True)
    res = cluster.actual_order("attack")
    lines = path.read_text().splitlines()
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["event"] == "agreement_round"
    assert rec["round"] == 0 and rec["n"] == 4 and rec["leader_id"] == 1
    assert rec["decision"] == res.decision
    assert rec["n_attack"] == res.n_attack
    assert rec["needed"] == res.needed and rec["total"] == res.total
    assert rec["nr_faulty"] == 1
    assert rec["round_elapsed_s"] >= 0 and "ts" in rec

    cluster.actual_order("retreat")
    lines = path.read_text().splitlines()
    assert len(lines) == 2 and json.loads(lines[1])["round"] == 1


def test_disabled_sink_writes_nothing(tmp_path, monkeypatch):
    _with_sink(monkeypatch, None)
    monkeypatch.delenv("BA_TPU_METRICS", raising=False)
    cluster = Cluster(3, PyBackend(), seed=1)
    assert cluster.actual_order("retreat") is not None
    assert not list(tmp_path.iterdir())


def test_sink_env_configuration(tmp_path, monkeypatch):
    path = tmp_path / "env.jsonl"
    monkeypatch.setenv("BA_TPU_METRICS", str(path))
    sink = metrics.MetricsSink()
    assert sink.enabled
    sink.emit({"event": "x"})
    assert json.loads(path.read_text())["event"] == "x"
