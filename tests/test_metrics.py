"""Observability: structured JSON-lines metrics from the agreement round.

SURVEY.md section 6: the reference has print()-only observability
(ba.py:255,389); the framework must do far better.  These pin the metrics
contract: one parseable line per ``actual-order``, with decision, vote
counts, quorum threshold, fault count, and wall time — and zero lines
(plus unchanged REPL output) when the sink is disabled.
"""

# The sink unit tests emit synthetic one-letter families ('x', 'a',
# 'late', ...) to exercise sink MECHANICS (enablement, env config,
# version stamping) — they are not real record contracts, so the
# schema-registry rule is waived file-wide here.
# ba-lint: disable-file=BA601

import json

from ba_tpu.runtime.backends import PyBackend
from ba_tpu.runtime.cluster import Cluster
from ba_tpu.utils import metrics


def _with_sink(monkeypatch, target):
    monkeypatch.setattr(metrics, "_default", metrics.MetricsSink(target))


def test_round_emits_one_json_line(tmp_path, monkeypatch):
    path = tmp_path / "metrics.jsonl"
    _with_sink(monkeypatch, str(path))
    cluster = Cluster(4, PyBackend(), seed=0)
    cluster.set_faulty(2, True)
    res = cluster.actual_order("attack")
    lines = path.read_text().splitlines()
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["event"] == "agreement_round"
    assert rec["round"] == 0 and rec["n"] == 4 and rec["leader_id"] == 1
    assert rec["decision"] == res.decision
    assert rec["n_attack"] == res.n_attack
    assert rec["needed"] == res.needed and rec["total"] == res.total
    assert rec["nr_faulty"] == 1
    assert rec["round_elapsed_s"] >= 0 and "ts" in rec

    cluster.actual_order("retreat")
    lines = path.read_text().splitlines()
    assert len(lines) == 2 and json.loads(lines[1])["round"] == 1


def test_disabled_sink_writes_nothing(tmp_path, monkeypatch):
    _with_sink(monkeypatch, None)
    monkeypatch.delenv("BA_TPU_METRICS", raising=False)
    cluster = Cluster(3, PyBackend(), seed=1)
    assert cluster.actual_order("retreat") is not None
    assert not list(tmp_path.iterdir())


def test_sink_env_configuration(tmp_path, monkeypatch):
    path = tmp_path / "env.jsonl"
    monkeypatch.setenv("BA_TPU_METRICS", str(path))
    sink = metrics.MetricsSink()
    assert sink.enabled
    sink.emit({"event": "x"})
    assert json.loads(path.read_text())["event"] == "x"


def test_every_record_carries_schema_version(tmp_path, monkeypatch):
    # ISSUE 2 satellite: the sink stamps "v": 1 on every record (callers
    # never spell it), alongside the wall-clock ts.  ts is correlation
    # only — every duration field is measured with perf_counter at its
    # call site, never derived from ts.
    path = tmp_path / "v.jsonl"
    _with_sink(monkeypatch, str(path))
    cluster = Cluster(3, PyBackend(), seed=2)
    cluster.actual_order("attack")
    metrics.emit({"event": "custom"})
    for line in path.read_text().splitlines():
        rec = json.loads(line)
        assert rec["v"] == metrics.SCHEMA_VERSION == 1
        assert "event" in rec and "ts" in rec


def test_sink_holds_one_handle(tmp_path, monkeypatch):
    # ISSUE 2 satellite: the first cut reopened the target on EVERY
    # emit; the sink now opens once (lazily), flushes per line, and
    # closes idempotently.
    path = tmp_path / "one.jsonl"
    sink = metrics.MetricsSink(str(path))
    opens = []
    real_open = open

    def counting_open(*a, **k):
        opens.append(a[0])
        return real_open(*a, **k)

    monkeypatch.setattr("builtins.open", counting_open)
    for i in range(5):
        sink.emit({"event": "n", "i": i})
    assert opens == [str(path)]  # one open across five emits
    # Flushed per line: readable before close, no buffering loss.
    assert len(path.read_text().splitlines()) == 5
    sink.close()
    sink.close()  # idempotent
    # emit after close lazily reopens (atexit-then-straggler safety).
    sink.emit({"event": "late"})
    sink.close()
    assert len(path.read_text().splitlines()) == 6


def test_sink_creates_parent_dir_and_survives_bad_target(tmp_path, capsys):
    # A sink path in a not-yet-existing directory is created lazily (the
    # common BA_TPU_METRICS=artifacts/run1/m.jsonl case)...
    path = tmp_path / "new" / "dir" / "m.jsonl"
    sink = metrics.MetricsSink(str(path))
    sink.emit({"event": "a"})
    sink.close()
    assert json.loads(path.read_text())["event"] == "a"
    # ...and a genuinely unwritable target warns ONCE and disables the
    # sink instead of crashing the agreement path (telemetry must never
    # kill the protocol; the reference's sin was silent swallowing, so
    # the warning is loud).
    bad = metrics.MetricsSink(str(tmp_path / "m.jsonl" / "x.jsonl"))
    (tmp_path / "m.jsonl").write_text("a file, not a dir")
    bad.emit({"event": "b"})
    assert not bad.enabled  # disabled after the failed open
    bad.emit({"event": "c"})  # silent no-op now
    err = capsys.readouterr().err
    assert err.count("metrics disabled") == 1


def test_sink_stderr_target(capsys):
    sink = metrics.MetricsSink("-")
    sink.emit({"event": "e"})
    err = capsys.readouterr().err
    rec = json.loads(err.strip())
    assert rec["event"] == "e" and rec["v"] == 1


def test_configure_replaces_default(tmp_path):
    old = metrics._default
    try:
        sink = metrics.configure(str(tmp_path / "c.jsonl"))
        assert metrics.default_sink() is sink
        metrics.emit({"event": "via_default"})
        sink.close()
        rec = json.loads((tmp_path / "c.jsonl").read_text())
        assert rec["event"] == "via_default"
    finally:
        metrics._default = old
