#!/usr/bin/env python
"""Perf-regression sentinel over the committed bench trajectory
(ISSUE 9).

The repo carries its performance history as committed artifacts —
``BENCH_*.json`` / ``MULTICHIP_*.json``, one or more per PR round, in
several generations of shape (driver-wrapped ``{"parsed": ...}`` heads,
per-config ``{"configs": {...}}`` lines, special-purpose span-budget and
multichip A/Bs) — but until now no machine read them: a PR that halved
``pipeline_sweep`` throughput would land silently.  This script is that
machine:

- **index**: every committed artifact normalizes into one trajectory
  table — ``{source, round, config, platform, rounds_per_sec,
  elapsed_s, ratios, acceptance}`` rows — and ``--index-only`` validates
  that every artifact still parses into it (a jax-free CI stage;
  ``--write BENCH_trajectory.json`` commits the table so future PRs
  diff a machine-readable perf history instead of re-reading prose).
- **compare**: ``--fresh DETAIL.json`` (repeatable) or ``--run`` (which
  invokes ``bench.py`` ``--reps`` times) compares a fresh run against
  the NEWEST committed baseline per (config, platform).  Fresh reps are
  paired per config with ``scripts/ab_common.py``'s ``paired_best`` —
  the same best-of-reps discipline the live A/B harness uses — and a
  config regresses when ``fresh/baseline < 1/threshold``.  The default
  threshold 2.0 matches the artifacts' own documented run-to-run noise
  ("shared TPU service: ~2x"); tighten with ``--threshold`` on quiet
  hosts.  Fresh acceptance booleans (``*_within_*``, ``bit_exact_*``,
  ``*bounded*``...) that read False are regressions regardless of rate.
- exits 0 green, 1 on regression, 2 on usage/parse errors — the shape a
  CI stage or a serving SLO check wants (ROADMAP direction 2: this is
  the template — swap committed artifacts for SLO targets).

Stdlib only except the optional ``ab_common`` import (same directory);
never imports jax or ba_tpu, so the index stages run anywhere.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import tempfile

TRAJECTORY_FORMAT = "ba_tpu.bench_trajectory"
TRAJECTORY_VERSION = 1

# Not part of the committed trajectory: the table itself, and the
# transient full-detail file bench.py rewrites on every invocation.
EXCLUDE = {"BENCH_trajectory.json", "BENCH_detail.json"}

_RATIO_KEY = re.compile(r"(speedup|_ratio|ratio_|overhead_frac|overhead_pct)")
_ACCEPT_KEY = re.compile(
    r"(within|bounded|bit_exact|_ok$|^ok$|recovery_within"
    r"|no_request_path_compiles"  # ISSUE 11: the warm-serving boolean
    r"|speedup_ge"  # ISSUE 16: signed_throughput's speedup_ge_3x gate
    r"|fired_and_cleared"  # ISSUE 17: serving_slo burn-alert lifecycle
    r"|all_spans_parented"  # ISSUE 19: fleet_trace tree completeness
    r"|merge_deterministic"  # ISSUE 19: fleet_trace shard-merge pin
    r"|reroute_zero_hung)"  # ISSUE 20: serving_fleet kill-drill boolean
)


def _round_of(path: str):
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else None


def _numeric(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _split_fields(blob: dict):
    """(ratios, acceptance) out of one config/artifact dict: numeric
    fields whose NAME declares a comparison, boolean fields whose name
    declares a bound."""
    ratios = {
        k: v for k, v in blob.items() if _numeric(v) and _RATIO_KEY.search(k)
    }
    acceptance = {
        k: v
        for k, v in blob.items()
        if isinstance(v, bool) and _ACCEPT_KEY.search(k)
    }
    return ratios, acceptance


def _row(source, rnd, config, platform, blob: dict) -> dict:
    ratios, acceptance = _split_fields(blob)
    return {
        "source": source,
        "round": rnd,
        "config": config,
        "platform": platform,
        "rounds_per_sec": (
            blob.get("rounds_per_sec")
            if _numeric(blob.get("rounds_per_sec"))
            else None
        ),
        "elapsed_s": (
            blob.get("elapsed_s") if _numeric(blob.get("elapsed_s")) else None
        ),
        "ratios": ratios,
        "acceptance": acceptance,
    }


def normalize_doc(path: str, doc: dict) -> list:
    """One artifact -> trajectory rows.  Raises ValueError on a shape no
    rule covers — the ``--index-only`` CI stage turns that into a red
    build instead of a silently unindexed artifact."""
    source = os.path.basename(path)
    rnd = _round_of(path)

    # Driver-wrapped heads ({"n": ..., "cmd": ..., "parsed": {...}}) and
    # driver multichip probes ({"n_devices": ..., "rc": ..., "ok": ...}).
    if "parsed" in doc:
        parsed = doc["parsed"]
        if isinstance(parsed, dict):
            return normalize_doc(path, parsed)
        return [
            _row(source, rnd, "driver", None, {"ok": doc.get("rc") == 0})
        ]
    if "n_devices" in doc and "rc" in doc:
        blob = {
            "ok": bool(doc.get("ok")),
            "skipped": bool(doc.get("skipped")),
        }
        # A skipped probe asserts nothing; a run one asserts its rc.
        acceptance = {} if blob["skipped"] else {"ok": blob["ok"]}
        row = _row(source, rnd, "multichip_driver", None, {})
        row["acceptance"] = acceptance
        return [row]

    metric = doc.get("metric")
    if metric is None:
        raise ValueError(f"{source}: no 'metric'/'parsed' key — unknown shape")

    platform = doc.get("platform")
    configs = doc.get("configs")
    if isinstance(configs, dict) and configs:
        rows = []
        for name, blob in sorted(configs.items()):
            if isinstance(blob, dict):
                rows.append(_row(source, rnd, name, platform, blob))
        if rows:
            return rows

    if metric == "span-budget":
        blob = dict(doc)
        # overhead_pct is the artifact's verdict; keep it as a ratio.
        return [_row(source, rnd, "span_budget", platform, blob)]
    if metric == "multichip-scenario-engine-ab":
        return [_row(source, rnd, "multichip", platform, dict(doc))]

    # Headline-only lines (the early BENCH_r0N heads): one row carrying
    # the primary metric value.
    blob = dict(doc)
    if _numeric(doc.get("value")) and "rounds_per_sec" not in blob:
        blob["rounds_per_sec"] = doc["value"]
    return [_row(source, rnd, "headline", platform, blob)]


def committed_artifacts(root: str) -> list:
    out = []
    for pattern in ("BENCH_*.json", "MULTICHIP_*.json"):
        out.extend(glob.glob(os.path.join(root, pattern)))
    return sorted(
        p for p in out if os.path.basename(p) not in EXCLUDE
    )


def build_index(paths: list) -> dict:
    rows, errors = [], []
    for path in paths:
        try:
            with open(path) as fh:
                doc = json.load(fh)
            got = normalize_doc(path, doc)
            if not got:
                raise ValueError(f"{path}: produced no rows")
            rows.extend(got)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: {e}")
    rows.sort(
        key=lambda r: (
            r["config"], r["round"] if r["round"] is not None else -1,
            r["source"],
        )
    )
    return {
        "format": TRAJECTORY_FORMAT,
        "v": TRAJECTORY_VERSION,
        "artifacts": len(paths),
        "rows": rows,
        "errors": errors,
    }


def newest_baselines(rows: list) -> dict:
    """{(config, platform): row} — the newest committed rate per config,
    keyed exactly as compare() looks them up.  ``round=None`` rows rank
    oldest (they predate the rN convention)."""
    best: dict = {}
    for row in rows:
        if row["rounds_per_sec"] is None:
            continue
        key = (row["config"], row["platform"])
        rnd = row["round"] if row["round"] is not None else -1
        cur = best.get(key)
        if cur is None or rnd >= (
            cur["round"] if cur["round"] is not None else -1
        ):
            best[key] = row
    return best


def compare(fresh_docs: list, baselines: dict, threshold: float):
    """Fresh bench docs vs the committed trajectory.  Returns
    ``(regressions, checked)``: how many configs regressed (rate below
    baseline/threshold, or a fresh acceptance boolean reading False)
    and how many were actually comparable — the caller must treat
    ``checked == 0`` as a configuration failure, never a pass (a
    platform or config-name drift would otherwise disable the gate
    silently, green forever)."""
    try:
        from ab_common import paired_best
    except ImportError:  # pragma: no cover - scripts/ not on sys.path
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ab_common import paired_best

    reps, accept_fails = [], []
    platforms: dict = {}
    for i, doc in enumerate(fresh_docs):
        rows = normalize_doc(f"fresh#{i}", doc)
        rep = {}
        for row in rows:
            rep[row["config"]] = row["rounds_per_sec"]
            platforms[row["config"]] = row["platform"]
            for name, v in row["acceptance"].items():
                if v is False:
                    accept_fails.append((row["config"], name))
        reps.append(rep)
    best = paired_best(reps)

    regressions = len(accept_fails)
    for config, name in accept_fails:
        print(f"RED  {config}: acceptance flag {name} is False")
    checked = 0
    for config, rate in sorted(best.items()):
        base = baselines.get((config, platforms.get(config)))
        if base is None or base["rounds_per_sec"] in (None, 0):
            print(f"new  {config}: {rate:.1f} rounds/s (no committed "
                  f"baseline at platform={platforms.get(config)})")
            continue
        checked += 1
        ratio = rate / base["rounds_per_sec"]
        verdict = "ok  "
        if ratio < 1.0 / threshold:
            verdict = "RED "
            regressions += 1
        print(
            f"{verdict} {config}: fresh {rate:.1f} vs baseline "
            f"{base['rounds_per_sec']:.1f} rounds/s "
            f"({base['source']}, r{base['round']}) ratio {ratio:.3f} "
            f"(threshold {1.0 / threshold:.3f})"
        )
    return regressions, checked


def run_fresh(repo: str, configs: str | None, reps: int) -> list:
    """Invoke ``bench.py`` ``reps`` times, collecting the full detail
    doc of each (BA_TPU_BENCH_DETAIL routed to a temp file).  Reps are
    whole-process so every rep pays the same setup — the per-config
    pairing happens in compare() via ``paired_best``."""
    docs = []
    for rep in range(reps):
        with tempfile.NamedTemporaryFile(
            suffix=".json", delete=False
        ) as tmp:
            detail = tmp.name
        try:
            cmd = [sys.executable, os.path.join(repo, "bench.py")]
            if configs:
                cmd += ["--configs", configs]
            env = dict(os.environ, BA_TPU_BENCH_DETAIL=detail)
            proc = subprocess.run(
                cmd, cwd=repo, env=env, capture_output=True, text=True
            )
            if proc.returncode != 0:
                print(
                    f"sentinel: bench rep {rep} failed rc="
                    f"{proc.returncode}\n{proc.stderr[-2000:]}",
                    file=sys.stderr,
                )
                raise SystemExit(2)
            with open(detail) as fh:
                docs.append(json.load(fh))
        finally:
            if os.path.exists(detail):
                os.unlink(detail)
    return docs


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--index-only", action="store_true",
                    help="build + validate the trajectory table and stop")
    ap.add_argument("--write", metavar="PATH",
                    help="write the trajectory table JSON to PATH")
    ap.add_argument("--fresh", action="append", default=[],
                    help="a fresh bench detail JSON to compare "
                         "(repeatable; reps pair per config)")
    ap.add_argument("--run", action="store_true",
                    help="invoke bench.py to produce the fresh side")
    ap.add_argument("--configs", default=None,
                    help="bench.py --configs for --run")
    ap.add_argument("--reps", type=int, default=1,
                    help="bench.py invocations for --run (best-of pairs "
                         "per config)")
    ap.add_argument("--threshold", type=float, default=2.0,
                    help="regression threshold: red when fresh < "
                         "baseline/threshold (default 2.0 — the "
                         "artifacts' documented run-to-run noise)")
    args = ap.parse_args()
    if args.threshold <= 1.0:
        ap.error(f"--threshold {args.threshold} must be > 1.0")

    paths = committed_artifacts(args.repo)
    if not paths:
        print(f"sentinel: no committed artifacts under {args.repo}",
              file=sys.stderr)
        return 2
    index = build_index(paths)
    if index["errors"]:
        for err in index["errors"]:
            print(f"sentinel: {err}", file=sys.stderr)
        return 2
    if args.write:
        with open(args.write, "w") as fh:
            json.dump(index, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"sentinel: wrote {len(index['rows'])} rows -> {args.write}")
    if args.index_only:
        print(
            f"sentinel: indexed {index['artifacts']} artifacts -> "
            f"{len(index['rows'])} trajectory rows, "
            f"{len(newest_baselines(index['rows']))} baselines"
        )
        return 0

    if args.run:
        fresh = run_fresh(args.repo, args.configs, args.reps)
    elif args.fresh:
        fresh = []
        for path in args.fresh:
            try:
                with open(path) as fh:
                    fresh.append(json.load(fh))
            except (OSError, ValueError) as e:
                print(f"sentinel: --fresh {path}: {e}", file=sys.stderr)
                return 2
    else:
        ap.error("give --index-only, --fresh FILE, or --run")
        return 2  # unreachable

    regressions, checked = compare(
        fresh, newest_baselines(index["rows"]), args.threshold
    )
    if regressions:
        print(f"sentinel: {regressions} regression(s)", file=sys.stderr)
        return 1
    if not checked:
        # Comparing NOTHING is not green: a platform string or config
        # name that drifted out of the baseline key set would otherwise
        # turn the gate off silently, on every future run.
        print(
            "sentinel: no comparable configs between the fresh run and "
            "the committed baselines — the gate compared nothing "
            "(platform/config drift?); refusing to report green",
            file=sys.stderr,
        )
        return 2
    print(f"sentinel: green ({checked} config(s) within threshold)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
