"""Shared harness for same-window fused-sweep A/Bs (tile_ab / rounds_ab).

One process, parameter variants interleaved within each rep so tunnel
service drift cancels; min-of-reps per variant.  Warm-up seeds are NEGATIVE
(-1 - variant) so no warm dispatch can ever be byte-identical to a timed
one (timed seeds are r*1000 + i, all >= 1) — a memoized repeat inside a
timed window would fake throughput (bench.py's tunnel-memoization note).
"""

from __future__ import annotations

import json


def sweep_fixture(batch: int = 10240, cap: int = 1024, m: int = 3):
    """The standard north-star A/B fixture: bucketed states + all-valid
    table verdicts, split per bucket."""
    import jax.numpy as jnp
    import jax.random as jr

    from ba_tpu.parallel import bucketed_sweep_states

    states = bucketed_sweep_states(jr.key(5), batch, cap, 2)
    ok = jnp.ones((batch, 2), bool)
    oks, off = [], 0
    for s in states:
        b = s.faulty.shape[0]
        oks.append(ok[off:off + b])
        off += b
    return states, oks


def interleaved_ab(steps: dict, iters: int, reps: int) -> dict:
    """Time each jitted ``steps[variant]`` (seed [1] int32 -> scalar)
    interleaved across variants; returns {variant: best elapsed_s}."""
    import jax
    import jax.numpy as jnp

    from bench import _timed  # the tunnel-safe timing single source of truth

    import sys

    alive = {}
    for idx, (k, step) in enumerate(steps.items()):  # compile+warm, off clock
        try:
            jax.device_get(step(jnp.asarray([-1 - idx], jnp.int32)))
            alive[k] = step
        except Exception as e:  # e.g. scoped-VMEM OOM at big tile x K
            print(f"variant {k} failed to compile: "
                  f"{str(e).splitlines()[0][:160]}", file=sys.stderr)
            alive[k] = None

    best = {k: float("inf") for k in steps}
    for r in range(reps):
        for k, step in alive.items():
            if step is None:
                continue
            mk = lambda i, _r=r: (jnp.asarray([_r * 1000 + i], jnp.int32),)
            best[k] = min(best[k], _timed(step, mk, iters, reps=1))
    return best


def paired_best(reps: list) -> dict:
    """Per-key best over repetition dicts — the same discipline
    ``interleaved_ab`` applies to live timings, lifted to any
    ``[{key: value}]`` series: each key's best (max) value across reps,
    so run-to-run service drift folds OUT of a comparison instead of
    into it.  ``scripts/bench_sentinel.py`` pairs fresh bench reps per
    config with this before diffing against the committed baseline
    (rates: higher is better; reps missing a key skip it)."""
    best: dict = {}
    for rep in reps:
        for k, v in rep.items():
            if v is None:
                continue
            if k not in best or v > best[k]:
                best[k] = v
    return best


def emit(metric: str, batch: int, iters: int, variants: dict, **extra):
    print(json.dumps({"metric": metric, "batch": batch, "iters": iters,
                      **extra, "variants": variants}))
