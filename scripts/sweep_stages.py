"""Per-stage breakdown of the north-star sweep step on the real chip.

Decomposes the signed-sweep step (bench_sweep10k_signed's one_bucket) into
its four sub-programs — round-1 broadcast, signature-mask gather, the m
collapsed relay rounds, and the quorum — each timed as its own jitted
program on device-resident inputs (the bench._timed playbook: host-fetch
sync, V distinct variants against tunnel memoization, min-of-reps).
``sum_of_stages ~ full_step`` (minus per-dispatch latency x stage count)
is the coverage cross-check.  Output: one JSON line.

Run ALONE (one TPU chip, one claim).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from bench import _timed
    from ba_tpu.core import sm_agreement
    from ba_tpu.core.om import round1_broadcast
    from ba_tpu.core.quorum import majority_counts, quorum_decision
    from ba_tpu.core.sm import (
        _initial_seen,
        sm_choice,
        sm_relay_rounds_collapsed,
    )
    from ba_tpu.crypto.signed import sig_valid_from_tables
    from ba_tpu.parallel import make_sweep_state

    batch = int(os.environ.get("SWEEP_STAGES_BATCH", 10240))
    cap = int(os.environ.get("SWEEP_STAGES_CAP", 1024))
    m = 3
    iters, reps = 20, 2
    V = reps * iters + 2
    state = make_sweep_state(jr.key(5), batch, cap)
    ok = jnp.ones((batch, 2), bool)
    keys = [jr.fold_in(jr.key(6), v) for v in range(V)]

    results = {}

    def timed(name, fn, make_args):
        elapsed = _timed(fn, make_args, iters, reps=reps)
        results[name] = {
            "ms_per_dispatch": round(elapsed / iters * 1e3, 3),
            "us_per_instance": round(elapsed / iters / batch * 1e6, 3),
        }
        return elapsed / iters

    t_total = 0.0

    # Stage 1: round-1 broadcast (coins + leader row scatter).
    fn_r1 = jax.jit(
        lambda k: round1_broadcast(k, state).astype(jnp.int32).sum()
    )
    t_total += timed("round1_broadcast", fn_r1, lambda i: (keys[i % V],))

    # Stage inputs: V distinct received rows, device-resident.  One jitted
    # callable reused across variants — a fresh jax.jit per iteration
    # would recompile the identical program V times through the tunnel.
    r1 = jax.jit(lambda k: round1_broadcast(k, state))
    recv = [r1(keys[v]) for v in range(V)]

    # Stage 2: signature-mask gather from the verified tables.
    fn_sig = jax.jit(
        lambda r: sig_valid_from_tables(ok, r).astype(jnp.int32).sum()
    )
    t_total += timed("sig_gather", fn_sig, lambda i: (recv[i % V],))

    # Stage 3: m collapsed relay rounds (seen init included — cheap mask).
    def relay(k, r):
        seen = _initial_seen(state, r)
        seen = sm_relay_rounds_collapsed(k, state, seen, m)
        return seen.astype(jnp.int32).sum()

    fn_relay = jax.jit(relay)
    t_total += timed(
        "relay_m%d" % m, fn_relay, lambda i: (keys[i % V], recv[i % V])
    )

    # Stage 4: choice + majority counts + quorum decision.
    mk_seen = jax.jit(
        lambda k, r: sm_relay_rounds_collapsed(
            k, state, _initial_seen(state, r), m
        )
    )
    seen_in = [mk_seen(keys[v], recv[v]) for v in range(V)]

    def quorum(seen):
        maj = sm_choice(state, seen)
        n_a, n_r, n_u = majority_counts(maj, state.alive)
        decision, _, _ = quorum_decision(n_a, n_r, n_u)
        return decision.astype(jnp.int32).sum()

    fn_q = jax.jit(quorum)
    t_total += timed("choice_quorum", fn_q, lambda i: (seen_in[i % V],))

    # Full step for the cross-check.
    @jax.jit
    def full(key):
        k1, k2 = jr.split(key)
        received = round1_broadcast(k1, state)
        sig_valid = sig_valid_from_tables(ok, received)
        out = sm_agreement(k2, state, m, None, sig_valid, received, True)
        return out["decision"].astype(jnp.int32).sum()

    t_full = timed("full_step", full, lambda i: (keys[i % V],))

    print(json.dumps({
        "metric": "sweep-stage-breakdown",
        "batch": batch, "n": cap, "m": m, "iters": iters,
        "sum_of_stages_ms": round((t_total) * 1e3, 3),
        "full_step_ms": round(t_full * 1e3, 3),
        "rounds_per_sec_full": round(batch / t_full, 1),
        **results,
    }))


if __name__ == "__main__":
    main()
