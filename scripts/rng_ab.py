"""A/B the sweep's RNG substrate on the real chip: threefry vs rbg.

The north-star sweep is VPU-bound with packed-u8 threefry draws as a major
term (BENCH_r02 "bound"); jax's "rbg" impl swaps ``jr.bits`` to XLA's
RngBitGenerator — the TPU's hardware generator — while keeping threefry
key derivation.  This script times the exact bench step (round-1 broadcast
-> signature gather -> collapsed relay -> quorum) under both impls and
prints one JSON line; it informs whether BENCH recommends BA_TPU_RNG=rbg.

Run ALONE (one TPU chip, one claim — see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from bench import _timed  # one source of truth for the tunnel-safe timing
    from ba_tpu.core import sm_agreement
    from ba_tpu.core.om import round1_broadcast
    from ba_tpu.crypto.signed import sig_valid_from_tables
    from ba_tpu.parallel import make_sweep_state

    batch, cap, m = 10240, 1024, 3
    iters = 50
    state = make_sweep_state(jr.key(5), batch, cap)
    ok = jnp.ones((batch, 2), bool)  # table-verify mask; content irrelevant here

    @jax.jit
    def step(key, state, ok):
        k1, k2 = jr.split(key)
        received = round1_broadcast(k1, state)
        sig_valid = sig_valid_from_tables(ok, received)
        out = sm_agreement(k2, state, m, None, sig_valid, received, True)
        return out["decision"].astype(jnp.int32).sum()

    results = {}
    for impl in ("threefry2x32", "rbg"):
        key = jr.key(6, impl=impl)
        best = _timed(step, lambda i: (jr.fold_in(key, i), state, ok), iters)
        results[impl] = {
            "elapsed_s": round(best, 4),
            "rounds_per_sec": round(batch * iters / best, 1),
        }
        print(f"{impl}: {results[impl]}", file=sys.stderr, flush=True)

    results["speedup_rbg"] = round(
        results["threefry2x32"]["elapsed_s"] / results["rbg"]["elapsed_s"], 3
    )
    print(json.dumps({"metric": "sweep-rng-ab", "batch": batch, "n_max": cap,
                      "m": m, "iters": iters, **results}))


if __name__ == "__main__":
    main()
