#!/usr/bin/env python
"""CI check: every JSONL record the obs layer emits parses and carries
``event`` + ``v`` (schema version).

Exercises the real emitters end-to-end — interactive rounds through the
sequential oracle backend (``agreement_round`` records), the pipelined
fallback path (``agreement_rounds`` decision tallies ride the sequential
records), a registry ``metrics_snapshot``, and (ISSUE 4) the DEVICE
tier: two tiny ``pipeline_sweep`` runs on the CPU backend at different
capacities drive the real ``compiled_artifact`` (obs/xla.py AOT
introspection) and ``recompile`` (obs/instrument.py explainer) emitters
— plus (ISSUES 7+9) a tiny SUPERVISED MESH campaign with a chaos plan
and the flight recorder + health sampler live, driving the real
``fault_injected``, ``recovery``, ``flight_span``, ``health_snapshot``
and assembled ``flight_summary`` emitters — plus (ISSUE 10) a short
deterministic SERVE session (queue-full rejection, shed-tier
transition, deadline expiry, two served cohorts) driving the real
``request``/``admission``/``shed`` emitters and the ``serve_*`` gauge
family (prefix-rule-checked) — plus (ISSUE 11) a WARM serve session
(background AOT warmup → warm barrier → one warm-dispatched request,
``compiles_on_request_path`` asserted 0) driving the real ``warmup``
record emitters (run_id-stamped) and the ``serve_warmup_*`` gauges —
plus (ISSUE 15) a short deterministic ADVERSARY SEARCH session (two
hunt generations, one checkpoint, one minimized finding) driving the
real ``search_generation``/``search_found``/``search_checkpoint``/
``search_minimized`` emitters and the ``search_*`` gauge family —
plus (ISSUE 16) a POOLED signed campaign (one explicit signing/verify
worker + a live signature-table cache) driving the real ``sign_pool``
emitter and the host sign/verify throughput gauges —
into a temp sink, then validates every line, including the typed shape of the device-tier, resilience, flight
and serving records, and the presence/shape of ``run_id`` on every
record family that carries it.  Run by ``scripts/ci.sh`` before
the tier-1 suite; standalone: ``JAX_PLATFORMS=cpu python
scripts/check_metrics_schema.py``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from ba_tpu import obs
    from ba_tpu.runtime.backends import PyBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.utils import metrics

    fd, path = tempfile.mkstemp(suffix=".jsonl")
    os.close(fd)
    try:
        sink = metrics.configure(path)
        cluster = Cluster(4, PyBackend(), seed=0)
        cluster.set_faulty(2, True)
        cluster.actual_order("attack")
        cluster.actual_order_rounds("retreat", 2)  # sequential fallback
        cluster.kill(1)  # election transition (registry counter, no emit)
        cluster.actual_order("attack")
        # Device tier: a live sink makes obs.xla.enabled() true, so two
        # tiny pipelined runs at DIFFERENT capacities exercise the real
        # compiled_artifact emitter and force one explained recompile.
        import jax.random as jr

        from ba_tpu.parallel import make_sweep_state, pipeline_sweep

        obs.reset_first_calls()
        # engine="xla" pinned EXPLICITLY on the baseline legs: with an
        # inherited BA_TPU_ENGINE the env default would move both
        # baselines onto the kernel engine and the engine-flip
        # assertion below would never see ["xla", "interpret"].
        pipeline_sweep(
            jr.key(0), make_sweep_state(jr.key(1), 4, 4), 2,
            with_counters=True, engine="xla",
        )
        pipeline_sweep(
            jr.key(2), make_sweep_state(jr.key(3), 4, 8), 2,
            with_counters=True, engine="xla",
        )
        # Engine-axis records (ISSUE 13): the SAME shapes through the
        # Pallas kernel (interpret mode — any host) force a recompile
        # whose ONLY changed axis is the engine: the explainer must
        # read `"engine": ["xla", "interpret"]`, type-checked below.
        pipeline_sweep(
            jr.key(2), make_sweep_state(jr.key(3), 4, 8), 2,
            with_counters=True, engine="interpret",
        )
        # Sign-ahead lane records (ISSUE 14): a tiny SIGNED campaign
        # drives the real sign_ahead emitter (one record per staged
        # window) and stamps the signed compile-signature axis; an
        # oral -> signed coalesced pair at EQUAL shapes then forces the
        # protocol-flip recompile the explainer must attribute to
        # exactly the signed axis.
        from ba_tpu.parallel.pipeline import coalesced_sweep, fresh_copy

        pipeline_sweep(
            jr.key(10), make_sweep_state(jr.key(11), 4, 4), 4,
            signed=True, rounds_per_dispatch=2, engine="xla",
        )
        _st_pair = make_sweep_state(jr.key(12), 2, 4)
        coalesced_sweep(
            [jr.key(13), jr.key(14)], fresh_copy(_st_pair), 2,
            rounds_per_dispatch=2,
        )
        coalesced_sweep(
            [jr.key(13), jr.key(14)], fresh_copy(_st_pair), 2,
            rounds_per_dispatch=2, signed=True,
        )
        # Host-crypto pool records (ISSUE 16): a tiny POOLED signed
        # campaign (one explicit worker, process defaults reset around
        # it) drives the real sign_pool emitter — workers/degraded/
        # cache tallies + the run_id the lane stamps (RUN_SCOPED_EVENTS
        # contract) — and leaves the host sign/verify throughput
        # gauges behind, both asserted below.
        from ba_tpu.crypto import pool as _sign_pool

        _saved_pool_env = {
            k: os.environ.get(k)
            for k in ("BA_TPU_SIGN_POOL", "BA_TPU_SIGN_CACHE")
        }
        os.environ["BA_TPU_SIGN_POOL"] = "1"
        os.environ["BA_TPU_SIGN_CACHE"] = "16"
        _sign_pool.shutdown_defaults()
        try:
            pipeline_sweep(
                jr.key(15), make_sweep_state(jr.key(16), 4, 4), 4,
                signed=True, rounds_per_dispatch=2, engine="xla",
            )
        finally:
            for k, v in _saved_pool_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            _sign_pool.shutdown_defaults()
        # Streaming-engine records (ISSUE 6): a tiny sparse campaign
        # with checkpoint_every drives the real scenario_checkpoint
        # emitter (carry serialization inside the retire fetch).
        from ba_tpu.scenario import compile_scenario, from_dict

        spec = from_dict(
            {"name": "ci", "rounds": 4,
             "events": [{"round": 1, "kill": [1]}]}
        )
        ck_path = path + ".carry.npz"
        pipeline_sweep(
            jr.key(4), make_sweep_state(jr.key(5), 4, 4), 4,
            scenario=compile_scenario(spec, 4, 4, sparse=True),
            rounds_per_dispatch=2, checkpoint_every=2,
            checkpoint_path=ck_path,
        )
        # Mesh-engine records (ISSUE 8): one campaign through the
        # sharded scan core (a 1x1 mesh — the sharded CODE PATH, no
        # device-count assumption on this host) drives the shard_layout
        # field on scenario_checkpoint and the per-shard gauges the
        # final metrics_snapshot must carry.
        from ba_tpu.parallel import make_mesh

        pipeline_sweep(
            jr.key(8), make_sweep_state(jr.key(9), 4, 4), 4,
            scenario=compile_scenario(spec, 4, 4, sparse=True),
            rounds_per_dispatch=2, checkpoint_every=2,
            checkpoint_path=path + ".mesh_carry.npz",
            mesh=make_mesh((1, 1), ("data", "node")),
        )
        # Resilience + flight-recorder records (ISSUES 7+9): a tiny
        # SUPERVISED MESH campaign with a chaos plan and the recorder
        # on (the sink is live, so every record carries the run's
        # run_id) drives the real fault_injected (chaos.py), recovery
        # (supervisor.py), flight_span (pipeline retire), and
        # health_snapshot (obs/health.py, health_every=1) emitters —
        # one in-place transient retry, one fatal -> checkpoint resume
        # — and the scope owner assembles the flight_summary at the
        # end.
        from ba_tpu.runtime import chaos
        from ba_tpu.runtime.supervisor import (
            SupervisorConfig, supervised_sweep,
        )

        plan = chaos.from_dict(
            {"name": "ci-chaos", "faults": [
                {"round": 0, "kind": "transient"},
                {"round": 2, "kind": "fatal"},
            ]}
        )
        supervised_sweep(
            jr.key(6), make_sweep_state(jr.key(7), 4, 4), 4,
            rounds_per_dispatch=2, chaos=plan,
            checkpoint_every=2, checkpoint_path=path + ".sup_{round}.npz",
            mesh=make_mesh((1, 1), ("data", "node")),
            health_every=1,
            config=SupervisorConfig(timeout_s=60.0, backoff_base_s=0.0),
        )
        # Serving-front-end records (ISSUE 10): a short deterministic
        # serve session drives the real request/admission/shed
        # emitters.  open() (admission without the dispatcher — the
        # documented drill hook) lets the queue fill deterministically:
        # one already-expired ticket + two live cohorts saturate
        # max_queue=3, the fourth submission rejects (admission
        # record), and start() then sheds (queue 3/3 -> tier 3, shed
        # record), expires the dead ticket (request/expired) and serves
        # the rest (request/ok carrying each cohort's run_id).
        from ba_tpu.runtime.serve import (
            AgreementRequest, AgreementService, Overloaded, ServeConfig,
        )

        svc = AgreementService(
            ServeConfig(
                max_batch=2, max_queue=3, coalesce_window_s=0.01,
                rounds_per_dispatch=2,
            )
        )
        svc.open()
        t_exp = svc.submit(
            AgreementRequest(kind="run-rounds", n=4, seed=1, rounds=3),
            deadline_s=0.0,
        )
        t_scn = svc.submit(
            AgreementRequest(kind="scenario", n=4, seed=2, spec=spec)
        )
        t_run = svc.submit(
            AgreementRequest(
                kind="run-rounds", n=4, faulty=(2,), seed=3, rounds=2
            )
        )
        overloaded = False
        try:
            svc.submit(AgreementRequest(kind="actual-order", n=4, seed=4))
        except Overloaded as e:
            overloaded = e.retry_after_s > 0
        assert overloaded, "queue-full submission did not reject"
        svc.start()
        t_scn.result(timeout=300)
        t_run.result(timeout=300)
        svc.stop()
        try:
            t_exp.result(timeout=1)
            print("schema check: expired ticket resolved", file=sys.stderr)
            return 1
        except Exception as e:
            if type(e).__name__ != "DeadlineExceeded":
                raise
        # Warm-serving records (ISSUE 11): a WARM serve session — open()
        # launches the background AOT warmup (one planned signature:
        # max_batch=1, one window), the warm barrier drains it, and one
        # request then dispatches off the precompiled executable —
        # driving the real warmup start/signature/done emitters (run_id
        # stamped on every one) and the serve_warmup_* gauge family the
        # final snapshot must carry.  The executable cache persists into
        # a temp dir so this check never touches user cache state.
        import tempfile as _tempfile

        with _tempfile.TemporaryDirectory() as aot_dir:
            warm_svc = AgreementService(
                ServeConfig(
                    max_batch=1, max_queue=4, coalesce_window_s=0.001,
                    rounds_per_dispatch=2, warm=True, warm_rounds=2,
                    aot_cache=aot_dir,
                )
            )
            warm_svc.open()
            if not warm_svc.warm_barrier(timeout=300):
                print("schema check: warm barrier timed out",
                      file=sys.stderr)
                return 1
            warm_svc.start()
            warm_svc.submit(
                AgreementRequest(kind="run-rounds", n=4, seed=5, rounds=2)
            ).result(timeout=300)
            # ISSUE 14 acceptance: the fleet INCLUDES a signed cohort
            # and the barrier-warmed service still never compiles on
            # the request path — the warmup lattice covers the signed
            # axis.
            warm_svc.submit(
                AgreementRequest(
                    kind="run-rounds", n=4, seed=6, rounds=2, signed=True
                )
            ).result(timeout=300)
            warm_stats = warm_svc.stats()
            warm_svc.stop()
        if warm_stats["compiles_on_request_path"] != 0:
            print(
                f"schema check: warm service (incl. a signed cohort) "
                f"compiled on the request path "
                f"({warm_stats['compiles_on_request_path']}x)",
                file=sys.stderr,
            )
            return 1

        # Adversary-search records (ISSUE 15): a short DETERMINISTIC
        # seeded hunt — two generations over a tiny space whose random
        # sweep is guaranteed to break IC (capacity 6 with up to 6
        # events finds t >= 2 campaigns immediately), one checkpointed
        # generation, one minimized finding — drives the real
        # search_generation / search_found / search_checkpoint /
        # search_minimized emitters, every one stamped with the hunt's
        # run_id (the RUN_SCOPED_EVENTS contract, validated below).
        from ba_tpu.search.generate import SearchSpace
        from ba_tpu.search.loop import hunt as search_hunt

        search_out = search_hunt(
            SearchSpace(
                rounds=4, capacity=6, population=8,
                events_min=2, events_max=5,
            ),
            seed=3, generations=2, objective="ic",
            minimize=True, minimize_max=1,
            checkpoint_path=path + ".search.json",
        )
        if not (
            search_out["stats"]["found"] >= 1
            and search_out["minimized"]
            and search_out["minimized"][0]["bit_exact"]
        ):
            print(
                f"schema check: search session found "
                f"{search_out['stats']['found']} violation(s), minimized "
                f"{search_out['minimized']} — the deterministic hunt "
                f"must find and shrink at least one",
                file=sys.stderr,
            )
            return 1

        # SLO-engine records (ISSUE 17): a TWO-TENANT serve session with
        # an installed SLO policy drives the real slo_report /
        # slo_alert / autoscale_signal emitters (every one
        # run_id-stamped — the RUN_SCOPED_EVENTS contract) plus the
        # tenant/cohort/phase-decomposition fields on request records;
        # stop() forces a final report, so at least one of each
        # reporting family is guaranteed.  The 1 ms latency objective
        # is unmeetable by construction — the burn alert must FIRE,
        # giving the slo_alert validator a real record.
        from ba_tpu.obs import slo as _slo

        slo_policy = _slo.SLOPolicy(
            objectives=(
                _slo.SLOObjective(
                    name="ci-wall", latency_s=0.001, target=0.5,
                    window_s=60.0, fast_window_s=5.0, slow_window_s=10.0,
                    burn_threshold=1.5,
                ),
            ),
            report_every_s=0.01,
        )
        slo_svc = AgreementService(
            ServeConfig(
                max_batch=2, max_queue=4, coalesce_window_s=0.005,
                rounds_per_dispatch=2, slo=slo_policy,
            )
        )
        slo_svc.start()
        slo_tickets = [
            slo_svc.submit(
                AgreementRequest(
                    kind="run-rounds", n=4, seed=20 + i, rounds=2,
                    tenant=("tenant-a" if i % 2 == 0 else "tenant-b"),
                )
            )
            for i in range(4)
        ]
        for t in slo_tickets:
            t.result(timeout=300)
        slo_stats = slo_svc.stats()
        slo_svc.stop()
        if not slo_stats["slo"]:
            print("schema check: SLO engine not wired", file=sys.stderr)
            return 1

        obs.default_registry().emit_snapshot(sink=sink, source="ci-check")
        sink.close()

        lines = [l for l in open(path).read().splitlines() if l.strip()]
        if not lines:
            print("schema check: no records emitted", file=sys.stderr)
            return 1
        bad = 0
        events = set()
        engine_flips = []  # ISSUE 13: recompile records' engine-axis pairs
        signed_flips = []  # ISSUE 14: recompile records' signed-axis pairs
        from ba_tpu.obs import flight as _flight

        # ONE schema table in the repo (ISSUE 18): the static registry
        # ba-lint's BA601/BA602 rules check emit sites against is the
        # same one this dynamic checker validates real streams against
        # — drift between the two is impossible by construction, and
        # the run-scope mirror is asserted outright.
        from ba_tpu.analysis import contracts

        if contracts.RUN_SCOPED_EVENTS != _flight.RUN_SCOPED_EVENTS:
            print(
                "schema check: analysis/contracts.RUN_SCOPED_EVENTS "
                "drifted from obs/flight.RUN_SCOPED_EVENTS: "
                f"{sorted(contracts.RUN_SCOPED_EVENTS ^ _flight.RUN_SCOPED_EVENTS)}",
                file=sys.stderr,
            )
            return 1

        def _num_or_null(v):
            return v is None or isinstance(v, (int, float))

        def _no_const(tok):  # strict JSON: Python json tolerates
            raise ValueError(f"non-strict JSON constant {tok!r}")  # Infinity/NaN

        for i, line in enumerate(lines):
            try:
                rec = json.loads(line, parse_constant=_no_const)
            except ValueError as e:
                print(f"schema check: line {i} unparseable: {e}", file=sys.stderr)
                bad += 1
                continue
            if "event" not in rec or rec.get("v") != metrics.SCHEMA_VERSION:
                print(
                    f"schema check: line {i} missing event/v: {line[:120]}",
                    file=sys.stderr,
                )
                bad += 1
            events.add(rec.get("event"))
            # Registry-driven generic validation: the family must be
            # DECLARED (an unknown event is an orphan stream ba-lint
            # would also flag at the emit site), and every key the
            # registry requires must be present on the wire.
            spec = contracts.RECORD_FAMILIES.get(rec.get("event"))
            if spec is None:
                print(
                    f"schema check: line {i} unknown record family "
                    f"{rec.get('event')!r} (not in analysis/contracts."
                    f"RECORD_FAMILIES): {line[:120]}",
                    file=sys.stderr,
                )
                bad += 1
            else:
                spec_missing = [
                    k for k in spec["required"] if k not in rec
                ]
                if spec_missing:
                    print(
                        f"schema check: line {i} {rec.get('event')} "
                        f"record missing required key(s) "
                        f"{spec_missing}: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            # Run correlation (ISSUE 9): every record family that is by
            # construction emitted from inside a campaign's run scope
            # must carry a well-formed run_id — and ANY record carrying
            # one must match the documented shape.
            rid = rec.get("run_id")
            if rec.get("event") in _flight.RUN_SCOPED_EVENTS and rid is None:
                print(
                    f"schema check: line {i} {rec.get('event')} record "
                    f"missing run_id: {line[:160]}",
                    file=sys.stderr,
                )
                bad += 1
            if rid is not None and not _flight.valid_run_id(rid):
                print(
                    f"schema check: line {i} malformed run_id {rid!r}: "
                    f"{line[:160]}",
                    file=sys.stderr,
                )
                bad += 1
            # Device-tier records carry a typed shape beyond event/v.
            if rec.get("event") == "compiled_artifact":
                numeric = (
                    "flops", "bytes_accessed", "argument_bytes",
                    "output_bytes", "temp_bytes", "alias_bytes",
                )
                if not (
                    isinstance(rec.get("fn"), str)
                    and isinstance(rec.get("axes"), dict)
                    and all(
                        isinstance(rec.get(f), (int, float)) for f in numeric
                    )
                    and isinstance(rec.get("donation_aliased"), bool)
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"compiled_artifact: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "recompile":
                changed = rec.get("changed")
                if not (
                    isinstance(rec.get("fn"), str)
                    and isinstance(changed, dict)
                    and changed
                    and all(
                        isinstance(v, list) and len(v) == 2
                        for v in changed.values()
                    )
                    and isinstance(rec.get("cross_process"), bool)
                ):
                    print(
                        f"schema check: line {i} malformed recompile: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
                elif "engine" in changed:
                    # ISSUE 13: the engine axis is a string pair out of
                    # the engine-request set (old may be null on a
                    # cross-process diff against a pre-engine row).
                    pair = changed["engine"]
                    if not all(
                        v is None or v in ("xla", "pallas", "interpret")
                        for v in pair
                    ):
                        print(
                            f"schema check: line {i} malformed engine "
                            f"axis: {line[:160]}",
                            file=sys.stderr,
                        )
                        bad += 1
                    else:
                        engine_flips.append(pair)
                if isinstance(changed, dict) and "signed" in changed:
                    # ISSUE 14: the signed axis is a bool pair (old may
                    # be null on a cross-process diff against a
                    # pre-signed-axis row).
                    pair = changed["signed"]
                    if not all(
                        v is None or isinstance(v, bool) for v in pair
                    ):
                        print(
                            f"schema check: line {i} malformed signed "
                            f"axis: {line[:160]}",
                            file=sys.stderr,
                        )
                        bad += 1
                    else:
                        signed_flips.append(pair)
            elif rec.get("event") == "recovery":
                if not (
                    rec.get("fault") in ("transient", "fatal", "oom")
                    and rec.get("action") in (
                        "resume", "degrade", "quarantine"
                    )
                    and isinstance(rec.get("attempt"), int)
                    and isinstance(rec.get("from_round"), int)
                    and isinstance(rec.get("lost_rounds"), int)
                    and isinstance(rec.get("error"), str)
                ):
                    print(
                        f"schema check: line {i} malformed recovery: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "fault_injected":
                if not (
                    isinstance(rec.get("plan"), str)
                    and rec.get("kind") in chaos.FAULT_KINDS
                    and rec.get("phase") in chaos.FAULT_PHASES
                    and isinstance(rec.get("round"), int)
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"fault_injected: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "scenario_checkpoint":
                layout = rec.get("shard_layout")
                if not (
                    isinstance(rec.get("round"), int)
                    and isinstance(rec.get("rounds"), int)
                    and isinstance(rec.get("bytes"), int)
                    and isinstance(rec.get("scenario"), bool)
                    and isinstance(rec.get("path"), str)
                    and isinstance(layout, dict)
                    and layout
                    and all(
                        isinstance(k, str)
                        and isinstance(v, int)
                        and v >= 1
                        for k, v in layout.items()
                    )
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"scenario_checkpoint: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "sign_ahead":
                # Sign-ahead lane records (ISSUE 14): one per staged
                # window of per-round signature tables.
                if not (
                    isinstance(rec.get("lo"), int)
                    and isinstance(rec.get("hi"), int)
                    and rec.get("lo") < rec.get("hi")
                    and isinstance(rec.get("batch"), int)
                    and rec.get("batch") >= 1
                    and isinstance(rec.get("values"), int)
                    and rec.get("values") >= 1
                    and isinstance(rec.get("wall_s"), (int, float))
                    and isinstance(rec.get("table_bytes"), int)
                ):
                    print(
                        f"schema check: line {i} malformed sign_ahead: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "sign_pool":
                # Host-crypto pool records (ISSUE 16): one per staged
                # window GROUP while a pool object is live — worker
                # census, degradation tally, cache hit/miss split and
                # the sign/verify/pool wall decomposition.  run_id is
                # required (RUN_SCOPED_EVENTS); its shape is validated
                # by the generic run_id pass above.
                if not (
                    isinstance(rec.get("workers"), int)
                    and rec.get("workers") >= 0
                    and isinstance(rec.get("requested"), int)
                    and rec.get("requested") >= 0
                    and isinstance(rec.get("degraded"), int)
                    and rec.get("degraded") >= 0
                    and isinstance(rec.get("rounds"), int)
                    and rec.get("rounds") >= 1
                    and isinstance(rec.get("cache_hits"), int)
                    and rec.get("cache_hits") >= 0
                    and isinstance(rec.get("cache_misses"), int)
                    and rec.get("cache_misses") >= 0
                    and isinstance(rec.get("sign_s"), (int, float))
                    and isinstance(rec.get("verify_s"), (int, float))
                    and isinstance(rec.get("pool_s"), (int, float))
                    and isinstance(rec.get("run_id"), str)
                ):
                    print(
                        f"schema check: line {i} malformed sign_pool: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "flight_span":
                if not (
                    rec.get("phase") == "retire"
                    and isinstance(rec.get("dispatch"), int)
                    and isinstance(rec.get("lo"), int)
                    and isinstance(rec.get("hi"), int)
                    and rec.get("lo") < rec.get("hi")
                    and isinstance(rec.get("latency_s"), (int, float))
                    and isinstance(rec.get("lag_s"), (int, float))
                ):
                    print(
                        f"schema check: line {i} malformed flight_span: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "health_snapshot":
                ints = ("rounds_total", "retires_total", "stalls_total")
                nums = (
                    "interval_s", "rounds_per_s", "depth_occupancy",
                    "retire_lag_p50_s", "retire_lag_p99_s",
                    "dispatch_latency_max_s", "watchdog_margin_s",
                    "plane_imbalance", "carry_imbalance",
                )
                if not (
                    all(isinstance(rec.get(f), int) for f in ints)
                    and all(_num_or_null(rec.get(f)) for f in nums)
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"health_snapshot: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "flight_summary":
                ckpts = rec.get("checkpoints")
                if not (
                    isinstance(rec.get("contiguous"), bool)
                    and isinstance(rec.get("windows"), int)
                    and isinstance(ckpts, list)
                    and all(
                        isinstance(c, dict)
                        and isinstance(c.get("round"), int)
                        and isinstance(c.get("path"), str)
                        and isinstance(c.get("shard_layout"), dict)
                        for c in ckpts
                    )
                    and isinstance(rec.get("recoveries"), list)
                    and isinstance(rec.get("faults"), list)
                    and isinstance(rec.get("recompiles"), list)
                    and isinstance(rec.get("timeline"), list)
                    and isinstance(rec.get("events"), dict)
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"flight_summary: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "request":
                # Serving front-end (ISSUE 10): terminal per-request
                # records; dispatched ("ok") ones carry their cohort's
                # run_id plus the slot→request mapping.
                ok_shape = (
                    isinstance(rec.get("id"), int)
                    and rec.get("kind")
                    in ("actual-order", "run-rounds", "scenario")
                    and rec.get("status") in ("ok", "failed", "expired")
                    and isinstance(rec.get("rounds"), int)
                    and isinstance(rec.get("queue_s"), (int, float))
                    and isinstance(rec.get("wall_s"), (int, float))
                    # SLO attribution (ISSUE 17): every terminal record
                    # carries the tenant label (string or null), the
                    # human cohort label, and ALL five phase fields
                    # (number-or-null — non-ok rows null what they
                    # never reached).
                    and (
                        rec.get("tenant") is None
                        or isinstance(rec.get("tenant"), str)
                    )
                    and isinstance(rec.get("cohort"), str)
                    and _num_or_null(rec.get("coalesce_s"))
                    and _num_or_null(rec.get("compile_s"))
                    and _num_or_null(rec.get("dispatch_s"))
                    and _num_or_null(rec.get("retire_lag_s"))
                )
                if ok_shape and rec["status"] == "ok":
                    ok_shape = (
                        _flight.valid_run_id(rec.get("run_id"))
                        and isinstance(rec.get("batch"), int)
                        and isinstance(rec.get("slot"), int)
                    )
                    # ok rows have the full decomposition: all five
                    # phases numeric and telescoping to the wall.
                    phases = [
                        rec.get(k)
                        for k in (
                            "queue_s", "coalesce_s", "compile_s",
                            "dispatch_s", "retire_lag_s",
                        )
                    ]
                    ok_shape = ok_shape and all(
                        isinstance(p, (int, float)) for p in phases
                    )
                    if ok_shape and abs(
                        sum(phases) - rec["wall_s"]
                    ) > 2e-3:
                        print(
                            f"schema check: line {i} request phase sum "
                            f"{sum(phases):.6f} != wall "
                            f"{rec['wall_s']:.6f}",
                            file=sys.stderr,
                        )
                        bad += 1
                if ok_shape and rec["status"] == "failed":
                    ok_shape = rec.get("fault") in (
                        None, "transient", "fatal", "oom",
                    )
                if not ok_shape:
                    print(
                        f"schema check: line {i} malformed request: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "admission":
                if not (
                    rec.get("decision") == "reject"
                    and rec.get("reason")
                    in ("queue_full", "shed_interactive", "shed_all")
                    and isinstance(rec.get("tier"), int)
                    and isinstance(rec.get("queue_depth"), int)
                    and isinstance(rec.get("queue_limit"), int)
                    and isinstance(rec.get("retry_after_s"), (int, float))
                    and rec.get("retry_after_s") > 0
                    # ISSUE 17: rejects carry tenant/cohort so the SLO
                    # engine can charge them to the right group.
                    and (
                        rec.get("tenant") is None
                        or isinstance(rec.get("tenant"), str)
                    )
                    and isinstance(rec.get("cohort"), str)
                ):
                    print(
                        f"schema check: line {i} malformed admission: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "slo_report":
                # SLO engine (ISSUE 17): per-window report — run_id
                # required (RUN_SCOPED_EVENTS), groups keyed by
                # (cohort, tenant), objectives carry burn rates.
                groups = rec.get("groups")
                objectives = rec.get("objectives")
                ok_shape = (
                    _flight.valid_run_id(rec.get("run_id"))
                    and isinstance(groups, list)
                    and isinstance(objectives, list)
                    and _num_or_null(rec.get("worst_burn"))
                    and _num_or_null(rec.get("worst_p99_s"))
                )
                if ok_shape:
                    for g in groups:
                        if not (
                            isinstance(g, dict)
                            and isinstance(g.get("cohort"), str)
                            and isinstance(g.get("tenant"), str)
                            and isinstance(g.get("window_events"), int)
                            and isinstance(g.get("counts"), dict)
                            and all(
                                isinstance(v, int)
                                for v in g["counts"].values()
                            )
                            and isinstance(g.get("phases"), dict)
                            and all(
                                isinstance(ph, dict)
                                and _num_or_null(ph.get("p50"))
                                and _num_or_null(ph.get("p99"))
                                for ph in g["phases"].values()
                            )
                            and isinstance(
                                g.get("attribution_checked"), int
                            )
                            and isinstance(g.get("attribution_bad"), int)
                        ):
                            ok_shape = False
                    for o in objectives:
                        if not (
                            isinstance(o, dict)
                            and isinstance(o.get("name"), str)
                            and isinstance(o.get("target"), (int, float))
                            and isinstance(
                                o.get("latency_s"), (int, float)
                            )
                            and isinstance(o.get("good"), int)
                            and isinstance(o.get("bad"), int)
                            and _num_or_null(o.get("burn_fast"))
                            and _num_or_null(o.get("burn_slow"))
                            and _num_or_null(o.get("burn"))
                            and _num_or_null(o.get("budget_remaining"))
                            and isinstance(o.get("alerting"), bool)
                        ):
                            ok_shape = False
                if not ok_shape:
                    print(
                        f"schema check: line {i} malformed slo_report: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "slo_alert":
                if not (
                    _flight.valid_run_id(rec.get("run_id"))
                    and isinstance(rec.get("objective"), str)
                    and rec.get("state") in ("fire", "clear")
                    and isinstance(rec.get("burn_fast"), (int, float))
                    and isinstance(rec.get("burn_slow"), (int, float))
                    and isinstance(rec.get("threshold"), (int, float))
                ):
                    print(
                        f"schema check: line {i} malformed slo_alert: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "autoscale_signal":
                if not (
                    _flight.valid_run_id(rec.get("run_id"))
                    and isinstance(rec.get("queue_frac"), (int, float))
                    and _num_or_null(rec.get("burn"))
                    and isinstance(rec.get("replicas"), int)
                    and isinstance(rec.get("recommended"), int)
                    and rec.get("recommended") >= 1
                    and isinstance(rec.get("reason"), str)
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"autoscale_signal: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "shed":
                if not (
                    isinstance(rec.get("tier"), int)
                    and isinstance(rec.get("prev_tier"), int)
                    and rec.get("tier") != rec.get("prev_tier")
                    and isinstance(rec.get("window_s"), (int, float))
                    and isinstance(rec.get("queue_depth"), int)
                    and _num_or_null(rec.get("retire_lag_p99_s"))
                    and _num_or_null(rec.get("depth_occupancy"))
                ):
                    print(
                        f"schema check: line {i} malformed shed: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "warmup":
                # Warm-serving records (ISSUE 11): every phase carries
                # the warmup pass's deterministic run_id; signature
                # rows name their fn/axes and a known status.
                ok_shape = (
                    rec.get("phase") in ("start", "signature", "done")
                    and _flight.valid_run_id(rec.get("run_id"))
                )
                if ok_shape and rec["phase"] == "start":
                    ok_shape = isinstance(rec.get("planned"), int)
                if ok_shape and rec["phase"] == "signature":
                    ok_shape = (
                        isinstance(rec.get("fn"), str)
                        and isinstance(rec.get("axes"), dict)
                        and rec.get("status")
                        in ("compiled", "loaded", "cached", "error")
                    )
                if ok_shape and rec["phase"] == "done":
                    ok_shape = (
                        isinstance(rec.get("planned"), int)
                        and isinstance(rec.get("warmed"), int)
                        and isinstance(rec.get("errors"), int)
                        and isinstance(rec.get("wall_s"), (int, float))
                    )
                if not ok_shape:
                    print(
                        f"schema check: line {i} malformed warmup: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "search_generation":
                # Adversary-search records (ISSUE 15): one per hunt
                # generation.
                if not (
                    isinstance(rec.get("generation"), int)
                    and isinstance(rec.get("campaigns"), int)
                    and rec.get("campaigns") >= 1
                    and isinstance(rec.get("best_score"), int)
                    and isinstance(rec.get("new_found"), int)
                    and isinstance(rec.get("found_total"), int)
                    and isinstance(rec.get("objective"), str)
                    and isinstance(rec.get("wall_s"), (int, float))
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"search_generation: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "search_found":
                if not (
                    isinstance(rec.get("name"), str)
                    and isinstance(rec.get("uid"), int)
                    and isinstance(rec.get("generation"), int)
                    and isinstance(rec.get("score"), int)
                    and rec.get("score") >= 1
                    and isinstance(rec.get("events"), int)
                    and isinstance(rec.get("counters"), dict)
                    and rec.get("counters")
                    and all(
                        isinstance(v, int)
                        for v in rec["counters"].values()
                    )
                    and isinstance(rec.get("objective"), str)
                ):
                    print(
                        f"schema check: line {i} malformed search_found: "
                        f"{line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "search_minimized":
                if not (
                    isinstance(rec.get("name"), str)
                    and isinstance(rec.get("uid"), int)
                    and isinstance(rec.get("events_before"), int)
                    and isinstance(rec.get("events_after"), int)
                    and rec.get("events_after") <= rec.get("events_before")
                    and isinstance(rec.get("evals"), int)
                    and isinstance(rec.get("score"), int)
                    and isinstance(rec.get("bit_exact"), bool)
                    and isinstance(rec.get("objective"), str)
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"search_minimized: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "search_checkpoint":
                if not (
                    isinstance(rec.get("generation"), int)
                    and isinstance(rec.get("path"), str)
                    and isinstance(rec.get("found"), int)
                ):
                    print(
                        f"schema check: line {i} malformed "
                        f"search_checkpoint: {line[:160]}",
                        file=sys.stderr,
                    )
                    bad += 1
            elif rec.get("event") == "metrics_snapshot":
                # Shard-labeled gauges (ISSUE 8): the engine stamps the
                # device count and per-device carry/plane byte shares
                # after every sweep — the weak-scaling denominators.
                metrics_blk = rec.get("metrics", {})
                # Metric-naming rules (ISSUE 10 serve_ prefix, ISSUE 8
                # _per_shard suffix) — delegated to the SAME registry
                # predicate ba-lint's BA602 applies at construction
                # sites, so the dynamic and static checkers cannot
                # disagree on what a well-formed name looks like.
                for name in metrics_blk:
                    reason = contracts.metric_name_violation(name)
                    if reason is not None:
                        print(
                            f"schema check: line {i} metric {name!r} "
                            f"naming violation: {reason}",
                            file=sys.stderr,
                        )
                        bad += 1
                for g in (
                    "serve_queue_depth",
                    "serve_shed_tier",
                    "serve_window_s",
                    # Warm-serving family (ISSUE 11): the warm session
                    # above must have left its warmup gauges and the
                    # request-path compile counter behind.
                    "serve_warmup_signatures",
                    "serve_warmup_pending",
                    "serve_warmup_warmed_total",
                    "serve_compile_on_request_path_total",
                ):
                    snap = metrics_blk.get(g)
                    if not (
                        isinstance(snap, dict)
                        and isinstance(snap.get("value"), (int, float))
                    ):
                        print(
                            f"schema check: line {i} metrics_snapshot "
                            f"missing/malformed gauge {g}: {line[:160]}",
                            file=sys.stderr,
                        )
                        bad += 1
                for g in (
                    # Adversary-search family (ISSUE 15): the hunt
                    # above must have left its gauges/counters behind.
                    "search_best_score",
                    "search_generations_total",
                    "search_campaigns_total",
                    "search_found_total",
                    "search_checkpoints_total",
                ):
                    snap = metrics_blk.get(g)
                    if not (
                        isinstance(snap, dict)
                        and isinstance(snap.get("value"), (int, float))
                    ):
                        print(
                            f"schema check: line {i} metrics_snapshot "
                            f"missing/malformed gauge {g}: {line[:160]}",
                            file=sys.stderr,
                        )
                        bad += 1
                for g in (
                    "pipeline_shards",
                    "pipeline_carry_bytes_per_shard",
                    "scenario_plane_bytes_per_shard",
                    # Sign-ahead lane family (ISSUE 14): the signed
                    # campaign above must have left its overlap gauge
                    # and window counter behind.
                    "host_sign_ahead_s",
                    "pipeline_sign_ahead_windows_total",
                    # Host-crypto pool family (ISSUE 16): the pooled
                    # signed campaign must have left the lane's
                    # throughput gauges and cache counters behind.
                    "host_sign_throughput_sigs_per_s",
                    "host_verify_throughput_sigs_per_s",
                    "sign_cache_hits_total",
                    "sign_cache_misses_total",
                ):
                    snap = metrics_blk.get(g)
                    if not (
                        isinstance(snap, dict)
                        and isinstance(snap.get("value"), (int, float))
                    ):
                        print(
                            f"schema check: line {i} metrics_snapshot "
                            f"missing/malformed gauge {g}: {line[:160]}",
                            file=sys.stderr,
                        )
                        bad += 1
        # The must-appear set is DERIVED from the registry (every
        # family whose spec has ci=True), not hand-listed here — add a
        # family to analysis/contracts.RECORD_FAMILIES and this check
        # starts demanding it on the wire automatically.
        want = set(contracts.CI_REQUIRED_EVENTS)
        if not want <= events:
            print(
                f"schema check: expected events {want - events} missing "
                f"(got {sorted(map(str, events))})",
                file=sys.stderr,
            )
            bad += 1
        if ["xla", "interpret"] not in engine_flips:
            # The interpret campaign above re-specialized at equal
            # shapes: the explainer must have attributed it to the
            # engine axis, and to exactly that flip.
            print(
                f"schema check: no recompile record explained the "
                f"engine flip (saw {engine_flips})",
                file=sys.stderr,
            )
            bad += 1
        if [False, True] not in signed_flips:
            # The oral -> signed coalesced pair above re-specialized at
            # equal shapes: the explainer must read the PROTOCOL flip
            # off the signed axis (ISSUE 14).
            print(
                f"schema check: no recompile record explained the "
                f"signed protocol flip (saw {signed_flips})",
                file=sys.stderr,
            )
            bad += 1
        if bad:
            return 1
        print(f"metrics JSONL schema OK ({len(lines)} records, v=1)")
        return check_sink_dir()
    finally:
        os.unlink(path)
        for ck in (".carry.npz", ".mesh_carry.npz", ".search.json"):
            if os.path.exists(path + ck):
                os.unlink(path + ck)
        import glob

        for stray in glob.glob(path + ".sup_*"):
            os.unlink(stray)


def check_sink_dir() -> int:
    """Fleet-tracing stage (ISSUE 19): drive a POOLED SIGNED serve
    session in sink-DIRECTORY mode — ``BA_TPU_METRICS`` set to a
    directory as an ENV var so the sign-pool workers inherit it, open
    their own ``<pid>.<token>.jsonl`` shards and land their
    ``pool_task`` spans in the fleet merge — then validate the three
    assembled families end-to-end: every shard leads with a typed
    ``clock_anchor``, every served request assembles into a
    ``request_trace`` whose non-root spans ALL resolve a parent and
    whose critical-path hop sum telescopes to the wall (the PR 17
    attribution invariant, re-checked across processes), and the
    stream folds into one typed ``fleet_summary``.  Required keys come
    from the SAME registry (``analysis/contracts.RECORD_FAMILIES``)
    ba-lint's BA601 checks the emit sites against."""
    import shutil
    import threading

    from ba_tpu.analysis import contracts
    from ba_tpu.crypto import pool as _sign_pool
    from ba_tpu.obs import fleet
    from ba_tpu.utils import metrics

    sink_dir = tempfile.mkdtemp(suffix=".fleet") + os.sep
    saved_env = {
        k: os.environ.get(k)
        for k in ("BA_TPU_METRICS", "BA_TPU_SIGN_POOL", "BA_TPU_SIGN_CACHE")
    }
    # The env var (not just programmatic configure) is load-bearing:
    # pool workers inherit their shard target through it.
    os.environ["BA_TPU_METRICS"] = sink_dir
    os.environ["BA_TPU_SIGN_POOL"] = "1"
    os.environ["BA_TPU_SIGN_CACHE"] = "16"
    _sign_pool.shutdown_defaults()
    try:
        metrics.configure(sink_dir)
        from ba_tpu.runtime.serve import (
            AgreementRequest, AgreementService, ServeConfig,
        )

        svc = AgreementService(
            ServeConfig(max_batch=4, max_queue=8, coalesce_window_s=0.02)
        )
        svc.start()
        errs = []

        def _go(i):
            try:
                svc.submit(
                    AgreementRequest(
                        kind="run-rounds", n=4, seed=40 + i, rounds=3,
                        m=1, signed=True,
                        tenant="tenant-a" if i % 2 == 0 else "tenant-b",
                    )
                ).result(timeout=300)
            except Exception as e:  # surfaced below, not swallowed
                errs.append(e)

        threads = [
            threading.Thread(target=_go, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        svc.stop()
        metrics.configure(None)
        if errs:
            print(f"sink-dir check: request failed: {errs[0]}",
                  file=sys.stderr)
            return 1

        bad = 0
        shards = fleet.list_shards(sink_dir)
        if len(shards) < 2:
            print(
                f"sink-dir check: expected main + worker shards, got "
                f"{[name for name, _ in shards]} — the pool "
                f"worker never opened its own shard",
                file=sys.stderr,
            )
            bad += 1
        # Every shard leads with its clock anchor (the alignment
        # contract merge_shards depends on).
        anchor_spec = contracts.RECORD_FAMILIES["clock_anchor"]
        for _name, sp in shards:
            recs = fleet.read_shard(sp)
            head = recs[0] if recs else {}
            if not (
                head.get("event") == "clock_anchor"
                and head.get("v") == metrics.SCHEMA_VERSION
                and all(k in head for k in anchor_spec["required"])
                and isinstance(head.get("pid"), int)
                and isinstance(head.get("shard"), str)
                and fleet.SHARD_RE.match(head["shard"])
                and isinstance(head.get("perf_t"), (int, float))
                and isinstance(head.get("ts"), (int, float))
            ):
                print(
                    f"sink-dir check: shard {_name} does "
                    f"not lead with a well-formed clock_anchor: {head}",
                    file=sys.stderr,
                )
                bad += 1
        merged = fleet.merge_shards(sink_dir)
        if fleet.merge_digest(merged) != fleet.merge_digest(
            fleet.merge_shards(sink_dir)
        ):
            print("sink-dir check: merge is not deterministic",
                  file=sys.stderr)
            bad += 1
        # The cross-process leg: worker pool_task spans, typed.
        pool_spec = contracts.RECORD_FAMILIES["pool_task"]
        pool_tasks = [r for r in merged if r.get("event") == "pool_task"]
        if not pool_tasks:
            print("sink-dir check: no pool_task record in any shard",
                  file=sys.stderr)
            bad += 1
        main_pid = os.getpid()
        for r in pool_tasks:
            if not (
                all(k in r for k in pool_spec["required"])
                and r.get("kind") in ("sign", "verify")
                and isinstance(r.get("rows"), int)
                and r.get("rows") >= 1
                and isinstance(r.get("wall_s"), (int, float))
                and isinstance(r.get("t_perf"), (int, float))
                # Worker provenance: the shard it landed in is not the
                # main process's.
                and int(fleet.SHARD_RE.match(r["shard"]).group(1))
                != main_pid
            ):
                print(
                    f"sink-dir check: malformed pool_task: {r}",
                    file=sys.stderr,
                )
                bad += 1
        # Every served request assembles into a fully-parented
        # cross-process trace within the attribution tolerance.
        trace_spec = contracts.RECORD_FAMILIES["request_trace"]
        rids = fleet.request_ids(merged)
        if len(rids) != 3:
            print(
                f"sink-dir check: expected 3 served requests, got {rids}",
                file=sys.stderr,
            )
            bad += 1
        hex_id = lambda s, n: (  # noqa: E731
            isinstance(s, str) and len(s) == n
            and all(c in "0123456789abcdef" for c in s)
        )
        for rid in rids:
            tr = fleet.assemble_request_trace(merged, request_id=rid)
            ok_shape = (
                tr is not None
                and tr.get("event") == "request_trace"
                and tr.get("v") == metrics.SCHEMA_VERSION
                and all(k in tr for k in trace_spec["required"])
                and hex_id(tr.get("trace_id"), 32)
                and tr.get("request_id") == rid
                and hex_id(tr.get("root_span"), 16)
                and isinstance(tr.get("spans"), list)
                and tr.get("span_count") == len(tr["spans"])
                and isinstance(tr.get("processes"), list)
                and len(tr["processes"]) >= 2
                and tr.get("unparented") == []
                and isinstance(tr.get("critical_path"), list)
                and all(
                    isinstance(h.get("hop"), str)
                    and isinstance(h.get("s"), (int, float))
                    for h in tr.get("critical_path", [])
                )
                and isinstance(tr.get("attribution_s"), (int, float))
                and isinstance(tr.get("wall_s"), (int, float))
                and tr.get("within_tol") is True
            )
            if not ok_shape:
                print(
                    f"sink-dir check: malformed request_trace for "
                    f"request {rid}: {tr}",
                    file=sys.stderr,
                )
                bad += 1
        # The stream folds into one typed fleet_summary.
        summary_spec = contracts.RECORD_FAMILIES["fleet_summary"]
        summary = fleet.fleet_summary(merged)
        if not (
            summary.get("event") == "fleet_summary"
            and summary.get("v") == metrics.SCHEMA_VERSION
            and all(k in summary for k in summary_spec["required"])
            and isinstance(summary.get("replicas"), list)
            and len(summary["replicas"]) >= 2
            and all(
                isinstance(rep.get("shard"), str)
                and isinstance(rep.get("pid"), int)
                and isinstance(rep.get("records"), int)
                for rep in summary["replicas"]
            )
            and isinstance(summary.get("cohorts"), list)
            and summary.get("requests") == len(rids)
            and isinstance(summary.get("pool_tasks"), int)
            and summary["pool_tasks"] >= 1
            and summary.get("traces") == len(rids)
        ):
            print(
                f"sink-dir check: malformed fleet_summary: {summary}",
                file=sys.stderr,
            )
            bad += 1
        if bad:
            return 1
        print(
            f"fleet sink-dir schema OK ({len(shards)} shards, "
            f"{len(merged)} records, {len(rids)} request traces, "
            f"{len(pool_tasks)} pool tasks)"
        )
        return check_fleet_router()
    finally:
        metrics.configure(None)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _sign_pool.shutdown_defaults()
        shutil.rmtree(sink_dir, ignore_errors=True)


def check_fleet_router() -> int:
    """Fleet-router stage (ISSUE 20): drive a 2-replica routed serve
    session plus ONE live serve-drain migration — routed requests
    through ``FleetRouter.submit``, a campaign drained mid-flight off
    ``replica-1`` and resumed on the survivor — then validate the three
    new record families end-to-end: every ``router_route`` is typed,
    ``run_id``-stamped and carries a parseable ``traceparent`` (routed
    admissions join the PR 19 causal trees), every ``replica_state``
    transition is within the pinned state machine and ``replica-1``
    walked ``ready → draining → stopped``, and the ``migration`` stream
    shows the full ``drain_start → handoff → resume`` lifecycle.
    Required keys come from ``analysis/contracts.RECORD_FAMILIES``,
    the same registry BA601 checks the emit sites against."""
    import shutil
    import threading
    import time

    from ba_tpu.analysis import contracts
    from ba_tpu.utils import metrics

    fd, path = tempfile.mkstemp(suffix=".router.jsonl")
    os.close(fd)
    root = tempfile.mkdtemp(suffix=".fleetroot")
    try:
        metrics.configure(path)
        from ba_tpu.fleet import (
            REPLICA_STATES,
            CampaignSpec,
            FleetConfig,
            FleetRouter,
            ReplicaManager,
        )
        from ba_tpu.runtime.serve import AgreementRequest, ServeConfig

        mgr = ReplicaManager(
            FleetConfig(replicas=2, root=root),
            serve_config=ServeConfig(
                max_queue=8, coalesce_window_s=0.01, warm=False
            ),
        )
        mgr.start()
        router = FleetRouter(mgr)
        errs = []

        def _go(i):
            try:
                router.submit(
                    AgreementRequest(
                        kind="run-rounds", n=4, seed=70 + i, rounds=2
                    ),
                    deadline_s=None,
                ).result(timeout=300)
            except Exception as e:  # surfaced below, not swallowed
                errs.append(e)

        threads = [
            threading.Thread(target=_go, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        handle = mgr.get("replica-1").run_campaign(CampaignSpec(
            campaign="schema-mig", seed=31, state_seed=32, batch=4,
            rounds=1200, capacity=4, checkpoint_every=8,
        ))
        deadline = time.perf_counter() + 120
        while handle.fingerprint is None and not handle.done():
            if time.perf_counter() > deadline:
                print("router check: campaign never checkpointed",
                      file=sys.stderr)
                return 1
            time.sleep(0.02)
        adopted = mgr.drain("replica-1")
        if errs:
            print(f"router check: routed request failed: {errs[0]}",
                  file=sys.stderr)
            return 1
        if handle.outcome != "handoff" or len(adopted) != 1:
            print(
                f"router check: expected one handoff migration, got "
                f"outcome={handle.outcome} adopted={len(adopted)}",
                file=sys.stderr,
            )
            return 1
        if not adopted[0].wait(300) or adopted[0].outcome != "completed":
            print(
                f"router check: resumed campaign did not complete "
                f"({adopted[0].outcome}: {adopted[0].error})",
                file=sys.stderr,
            )
            return 1
        mgr.stop()
        metrics.configure(None)

        with open(path, encoding="utf-8") as f:
            recs = [json.loads(line) for line in f if line.strip()]
        by_event: dict = {}
        for r in recs:
            by_event.setdefault(r.get("event"), []).append(r)
        bad = 0
        replica_names = {r.name for r in mgr.all()}

        routes = by_event.get("router_route", [])
        route_spec = contracts.RECORD_FAMILIES["router_route"]
        if len(routes) < 3:
            print(f"router check: expected >= 3 router_route records, "
                  f"got {len(routes)}", file=sys.stderr)
            bad += 1
        for r in routes:
            if not (
                all(k in r for k in route_spec["required"])
                and isinstance(r.get("request_id"), int)
                and isinstance(r.get("cohort"), str)
                and r.get("replica") in replica_names
                and isinstance(r.get("hops"), int)
                and r["hops"] >= 1
                and isinstance(r.get("rerouted"), bool)
                # run_id + traceparent presence (the ISSUE 20
                # satellite): routed admissions are run-scoped AND
                # join the causal trees.
                and r.get("run_id") == mgr.run_id
                and metrics.parse_traceparent(r.get("traceparent"))
                is not None
            ):
                print(f"router check: malformed router_route: {r}",
                      file=sys.stderr)
                bad += 1
        states = by_event.get("replica_state", [])
        state_spec = contracts.RECORD_FAMILIES["replica_state"]
        for r in states:
            if not (
                all(k in r for k in state_spec["required"])
                and r.get("replica") in replica_names
                and r.get("state") in REPLICA_STATES
                and r.get("prev") in REPLICA_STATES
                and r.get("run_id") == mgr.run_id
            ):
                print(f"router check: malformed replica_state: {r}",
                      file=sys.stderr)
                bad += 1
        walked = [
            (r["prev"], r["state"]) for r in states
            if r.get("replica") == "replica-1"
        ]
        for edge in (
            ("new", "booting"), ("booting", "ready"),
            ("ready", "draining"), ("draining", "stopped"),
        ):
            if edge not in walked:
                print(
                    f"router check: replica-1 never walked {edge} "
                    f"(saw {walked})",
                    file=sys.stderr,
                )
                bad += 1
        migrations = by_event.get("migration", [])
        mig_spec = contracts.RECORD_FAMILIES["migration"]
        for r in migrations:
            if not (
                all(k in r for k in mig_spec["required"])
                and isinstance(r.get("phase"), str)
                and isinstance(r.get("campaign"), str)
                and r.get("from_replica") in replica_names
            ):
                print(f"router check: malformed migration: {r}",
                      file=sys.stderr)
                bad += 1
        phases = {r.get("phase") for r in migrations}
        if not {"drain_start", "handoff", "resume"} <= phases:
            print(
                f"router check: migration lifecycle incomplete "
                f"(saw phases {sorted(phases)})",
                file=sys.stderr,
            )
            bad += 1
        if bad:
            return 1
        print(
            f"fleet router schema OK ({len(routes)} routes, "
            f"{len(states)} replica_state transitions, "
            f"{len(migrations)} migration records)"
        )
        return 0
    finally:
        metrics.configure(None)
        os.unlink(path)
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
