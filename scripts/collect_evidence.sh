#!/usr/bin/env bash
# Collect the round's TPU evidence artifacts in one sequential pass.
#
# Produces (in the repo root):
#   BENCH_local_r{N}.json  - full bench suite (all configs, one JSON line)
#   STAGES_r{N}.json       - per-kernel verify-pipeline breakdown + VPU peak
#   TESTS_TPU_r{N}.txt     - the TPU-gated Mosaic-kernel test transcript
#   LATENCY_r{N}.jsonl     - REPL metrics incl. per-round round_elapsed_s
#
# Run it ALONE: nothing else may touch the TPU while it runs (a second
# default-backend process blocks on the chip lease and can wedge both).
set -u
N="${1:?usage: collect_evidence.sh <round number, e.g. 3>}"
cd "$(dirname "$0")/.."

MANIFEST="EVIDENCE_r${N}.manifest"
: > "$MANIFEST"
fail=0
step() {  # step <name> <artifact> -- cmd...
    local name="$1" artifact="$2"; shift 2; shift  # drop '--'
    echo "== $name"
    "$@"
    local rc=$?
    echo "$name exit=$rc artifact=$artifact $(date -u +%FT%TZ)" >> "$MANIFEST"
    echo "   exit $rc ($(date))"
    [ "$rc" -ne 0 ] && fail=1
}

# bench.py's stdout is now the compact headline line (driver tail-window
# contract); the full per-config artifact is the BA_TPU_BENCH_DETAIL file.
step "bench" "BENCH_local_r${N}.json" -- \
    bash -c "BA_TPU_BENCH_DETAIL='BENCH_local_r${N}.json' python bench.py \
             > '/tmp/bench_compact_r${N}.json' 2> '/tmp/bench_r${N}.err'"

step "stages" "STAGES_r${N}.json" -- \
    bash -c "python bench.py --stages > 'STAGES_r${N}.json' 2> '/tmp/stages_r${N}.err'"

step "tpu-tests" "TESTS_TPU_r${N}.txt" -- \
    bash -c "BA_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_ops.py -q \
             > 'TESTS_TPU_r${N}.txt' 2>&1"

# The metrics sink appends; start the latency artifact fresh so reruns
# never mix stale rounds in.
rm -f "LATENCY_r${N}.jsonl"
step "repl-latency" "LATENCY_r${N}.jsonl" -- \
    bash -c "printf 'actual-order attack\nactual-order retreat\nactual-order attack\nExit\n' \
             | BA_TPU_METRICS='LATENCY_r${N}.jsonl' ./Generals_Byzantine_program.sh 4 \
             > '/tmp/repl_r${N}.out' 2>&1"

echo "done (fail=$fail); manifest:"
cat "$MANIFEST"
exit "$fail"
