#!/usr/bin/env bash
# Collect the round's TPU evidence artifacts in one sequential pass.
#
# Produces (in the repo root):
#   BENCH_local_r{N}.json  - full bench suite (all configs, one JSON line)
#   STAGES_r{N}.json       - per-kernel verify-pipeline breakdown + VPU peak
#   TESTS_TPU_r{N}.txt     - the TPU-gated Mosaic-kernel test transcript
#   LATENCY_r{N}.jsonl     - REPL metrics incl. per-round round_elapsed_s
#
# Run it ALONE: nothing else may touch the TPU while it runs (a second
# default-backend process blocks on the chip lease and can wedge both).
set -u
N="${1:?usage: collect_evidence.sh <round number, e.g. 3>}"
cd "$(dirname "$0")/.."

echo "== [1/4] bench suite"
python bench.py > "BENCH_local_r${N}.json" 2> "/tmp/bench_r${N}.err"
echo "   exit $? ($(date))"

echo "== [2/4] stage breakdown"
python bench.py --stages > "STAGES_r${N}.json" 2> "/tmp/stages_r${N}.err"
echo "   exit $? ($(date))"

echo "== [3/4] TPU-gated kernel tests"
BA_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_ops.py -q \
    > "TESTS_TPU_r${N}.txt" 2>&1
echo "   exit $? ($(date))"

echo "== [4/4] interactive REPL latency (metrics sink)"
printf 'actual-order attack\nactual-order retreat\nactual-order attack\nExit\n' \
    | BA_TPU_METRICS="LATENCY_r${N}.jsonl" ./Generals_Byzantine_program.sh 4 \
    > "/tmp/repl_r${N}.out" 2>&1
echo "   exit $? ($(date))"

echo "done; artifacts: BENCH_local_r${N}.json STAGES_r${N}.json TESTS_TPU_r${N}.txt LATENCY_r${N}.jsonl"
