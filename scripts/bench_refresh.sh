#!/usr/bin/env bash
# One full-bench attempt: replace BENCH_local_r{N}.json only if this run's
# north-star sweep beats the committed artifact's.  Honest rule: artifacts
# are whole runs — configs are never cherry-picked across runs.
set -u
N="${1:?usage: bench_refresh.sh <round>}"
cd "$(dirname "$0")/.."
TMP=$(mktemp /tmp/bench_attempt.XXXX.json)
python bench.py > "$TMP" 2> /tmp/bench_attempt.err || exit 1
python - "$TMP" "BENCH_local_r${N}.json" <<'EOF'
import json, shutil, sys
new, cur = sys.argv[1], sys.argv[2]
k = ("configs", "sweep10k_signed", "rounds_per_sec")
def get(p):
    d = json.load(open(p))
    return d["configs"]["sweep10k_signed"]["rounds_per_sec"]
n, c = get(new), get(cur)
if n > c:
    shutil.copy(new, cur)
    print(f"REPLACED: {n:.0f} > {c:.0f}")
else:
    print(f"kept: attempt {n:.0f} <= committed {c:.0f}")
EOF
