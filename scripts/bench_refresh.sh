#!/usr/bin/env bash
# One full-bench attempt.  Every attempt is APPENDED to
# BENCH_attempts_r{N}.jsonl (timestamped), so the committed artifact can be
# judged against the whole window distribution instead of silently
# ratcheting toward the noise ceiling (ADVICE r3: replace-only-if-better
# alone drifts the headline to the best weather ever seen).  The committed
# BENCH_local_r{N}.json is still replaced only when the north-star sweep
# beats it, and artifacts stay whole runs — configs are never
# cherry-picked across runs.
set -u
N="${1:?usage: bench_refresh.sh <round>}"
cd "$(dirname "$0")/.."
TMP=$(mktemp /tmp/bench_attempt.XXXX.json)
BA_TPU_BENCH_DETAIL="$TMP" python bench.py > /tmp/bench_compact.json \
    2> /tmp/bench_attempt.err || exit 1
python - "$TMP" "BENCH_local_r${N}.json" "BENCH_attempts_r${N}.jsonl" <<'EOF'
import datetime, json, shutil, sys
new, cur, log = sys.argv[1], sys.argv[2], sys.argv[3]
def star(p):
    d = json.load(open(p))
    return d["configs"]["sweep10k_signed"]["rounds_per_sec"]
n = star(new)
attempt = json.load(open(new))
attempt["attempt_utc"] = datetime.datetime.now(
    datetime.timezone.utc
).isoformat(timespec="seconds")
with open(log, "a") as f:
    f.write(json.dumps(attempt) + "\n")
rates = sorted(
    json.loads(l)["configs"]["sweep10k_signed"]["rounds_per_sec"]
    for l in open(log)
)
dist = (f"attempts n={len(rates)} min={rates[0]:.0f} "
        f"median={rates[len(rates) // 2]:.0f} max={rates[-1]:.0f}")
try:
    c = star(cur)
except FileNotFoundError:
    c = float("-inf")
if n > c:
    shutil.copy(new, cur)
    print(f"REPLACED: {n:.0f} > {c:.0f} | {dist}")
else:
    print(f"kept: attempt {n:.0f} <= committed {c:.0f} | {dist}")
EOF
