#!/usr/bin/env python
"""Render a ba_tpu obs artifact pair into a human summary.

Usage:
    python scripts/obs_report.py DIR                 # bench.py --obs DIR
    python scripts/obs_report.py --trace trace.json --metrics metrics.jsonl
    python scripts/obs_report.py DIR --flight [--run-id ID]   # ISSUE 9

Reads the Chrome trace-event JSON written by ``obs.trace`` (span
durations grouped by name) and/or the JSONL sink stream (event counts
plus the last ``metrics_snapshot``'s counters, gauges, and histogram
buckets) and prints aligned tables — the zero-dependency way to answer
"where did the time go" without opening Perfetto.  ``--flight`` renders
the assembled ``flight_summary`` record instead: one campaign run's
correlated dispatch→retire→checkpoint→recovery timeline, shard
provenance and recompile attribution (``obs/flight.py`` assembles at
end-of-run; this renders what the stream carries).

Stdlib only; never imports jax or ba_tpu (it must run anywhere the
artifacts were copied to).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _fmt_s(seconds: float) -> str:
    if seconds == float("inf"):
        return "+Inf"
    if seconds >= 1.0:
        return f"{seconds:.3f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.2f} ms"
    return f"{seconds * 1e6:.1f} us"


def report_trace(path: str) -> None:
    with open(path) as fh:
        doc = json.load(fh)
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    spans: dict = {}
    instants: dict = {}
    for ev in events:
        if ev.get("ph") == "X":
            spans.setdefault(ev["name"], []).append(ev.get("dur", 0.0))
        elif ev.get("ph") == "i":
            instants[ev["name"]] = instants.get(ev["name"], 0) + 1
    print(f"== spans ({path}) ==")
    if not spans:
        print("  (no spans recorded — was BA_TPU_TRACE/--obs enabled?)")
    else:
        header = f"  {'name':<24} {'count':>6} {'total':>12} {'mean':>12} {'max':>12}"
        print(header)
        by_total = sorted(
            spans.items(), key=lambda kv: sum(kv[1]), reverse=True
        )
        for name, durs_us in by_total:
            total = sum(durs_us) / 1e6  # trace-event ts/dur are microseconds
            print(
                f"  {name:<24} {len(durs_us):>6} {_fmt_s(total):>12} "
                f"{_fmt_s(total / len(durs_us)):>12} "
                f"{_fmt_s(max(durs_us) / 1e6):>12}"
            )
    if instants:
        print("== markers ==")
        for name, c in sorted(instants.items()):
            print(f"  {name:<24} {c:>6}")


def _hist_quantile(buckets: list, count: int, q: float) -> float | None:
    """Approximate quantile: the upper edge of the bucket where the
    cumulative count crosses q*count (None for an empty histogram).
    The overflow edge is serialized as the string "+Inf"."""
    if not count:
        return None
    need = q * count
    cum = 0
    for le, c in buckets:
        cum += c
        if cum >= need:
            return float("inf") if le == "+Inf" else le
    return None


def _fmt_count(n: float) -> str:
    """Engineering-notation counts (flops/bytes): 1.23e9 -> '1.23 G'."""
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(n) >= scale:
            return f"{n / scale:.2f} {suffix}"
    return f"{n:g}"


def report_device(artifacts: list, recompiles: list) -> None:
    """The device tier: compiled-artifact cost table, donation-alias
    verification, and the recompile ledger (obs/xla.py +
    obs/instrument.py's recompile explainer)."""
    if artifacts:
        print("== compiled artifacts (device tier) ==")
        print(
            f"  {'fn':<24} {'flops':>10} {'bytes_acc':>10} {'arg':>10} "
            f"{'out':>10} {'temp':>10} {'alias':>10}"
        )
        for a in artifacts:
            print(
                f"  {a.get('fn', '?'):<24} "
                f"{_fmt_count(a.get('flops', 0)):>10} "
                f"{_fmt_count(a.get('bytes_accessed', 0)):>10} "
                f"{_fmt_count(a.get('argument_bytes', 0)):>10} "
                f"{_fmt_count(a.get('output_bytes', 0)):>10} "
                f"{_fmt_count(a.get('temp_bytes', 0)):>10} "
                f"{_fmt_count(a.get('alias_bytes', 0)):>10}"
            )
        print("== donation-alias verification ==")
        for a in artifacts:
            alias = a.get("alias_bytes", 0)
            verdict = (
                f"aliased {_fmt_count(alias)}B of inputs onto outputs "
                "(donation held)"
                if alias
                else "NO aliasing — donate_argnums had no effect"
            )
            print(f"  {a.get('fn', '?'):<24} {verdict}")
    if recompiles:
        print("== recompile ledger ==")
        for r in recompiles:
            changes = ", ".join(
                f"{axis}: {old!r} -> {new!r}"
                for axis, (old, new) in sorted(r.get("changed", {}).items())
            )
            print(f"  {r.get('fn', '?'):<24} {changes}")


def report_flight(path: str, run_id: str | None = None) -> int:
    """Render a run's assembled ``flight_summary`` (ISSUE 9) from the
    JSONL stream: the correlated dispatch→retire→checkpoint→recovery
    timeline, shard provenance, and recompile attribution.  Reads the
    summary RECORD the scope owner appended at end-of-run (the engine
    assembles; this renders) — ``run_id=None`` takes the stream's last.
    """
    summary = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("event") != "flight_summary":
                continue
            if run_id is None or rec.get("run_id") == run_id:
                summary = rec  # last wins: the freshest assembly
    if summary is None:
        which = f" for run {run_id!r}" if run_id else ""
        print(f"(no flight_summary record{which} in {path} — was the "
              f"campaign run with a file-backed metrics sink?)",
              file=sys.stderr)
        return 1
    rounds = summary.get("rounds")
    print(f"== flight {summary.get('run_id')} ==")
    print(
        f"  rounds     {rounds[0]}..{rounds[1]}" if rounds
        else "  rounds     (none retired)"
    )
    print(f"  contiguous {summary.get('contiguous')}")
    print(f"  windows    {summary.get('windows')}")
    lat = summary.get("dispatch_latency_max_s")
    if lat is not None:
        print(f"  worst dispatch latency {_fmt_s(lat)}")
    layout = summary.get("shard_layout")
    if layout:
        print("  shard layout " + ", ".join(
            f"{k}={v}" for k, v in sorted(layout.items())
        ))
    per_shard = summary.get("per_shard")
    if per_shard:
        for k, v in sorted(per_shard.items()):
            print(f"  {k:<34} {v}")
    ckpts = summary.get("checkpoints") or []
    if ckpts:
        print("== checkpoints ==")
        for c in ckpts:
            extra = ""
            if c.get("shard_layout"):
                extra = "  layout " + ",".join(
                    f"{k}={v}" for k, v in sorted(c["shard_layout"].items())
                )
            print(f"  round {c.get('round'):>8}  "
                  f"{_fmt_count(c.get('bytes') or 0)}B  "
                  f"{c.get('path')}{extra}")
    recoveries = summary.get("recoveries") or []
    if recoveries:
        print("== recoveries ==")
        for r in recoveries:
            print(f"  {r.get('fault'):<10} {r.get('action'):<10} "
                  f"from round {r.get('from_round')} "
                  f"(lost {r.get('lost_rounds')}): {r.get('error', '')}")
    faults = summary.get("faults") or []
    if faults:
        print("== injected faults ==")
        for f in faults:
            print(f"  {f.get('kind'):<10} {f.get('phase'):<10} "
                  f"round {f.get('round')} (plan {f.get('plan')})")
    recompiles = summary.get("recompiles") or []
    if recompiles:
        print("== recompiles ==")
        for r in recompiles:
            changes = ", ".join(
                f"{axis}: {old!r} -> {new!r}"
                for axis, (old, new) in sorted(
                    (r.get("changed") or {}).items()
                )
            )
            cross = " [cross-process]" if r.get("cross_process") else ""
            print(f"  {r.get('fn', '?'):<24} {changes}{cross}")
    health = summary.get("last_health")
    if health:
        print("== last health sample ==")
        for k in (
            "rounds_per_s", "depth_occupancy", "retire_lag_p50_s",
            "retire_lag_p99_s", "watchdog_margin_s", "plane_imbalance",
            "carry_imbalance",
        ):
            v = health.get(k)
            if v is not None:
                time_like = k.endswith("_s") and not k.endswith("_per_s")
                print(f"  {k:<24} {_fmt_s(v) if time_like else v}")
    timeline = summary.get("timeline") or []
    if timeline:
        print(f"== timeline ({len(timeline)} events) ==")
        for e in timeline:
            kind = e.get("kind")
            if kind == "dispatch_window":
                desc = (f"rounds [{e.get('lo')}, {e.get('hi')}) "
                        f"dispatch {e.get('dispatch')}")
            elif kind == "checkpoint":
                desc = f"round {e.get('round')} -> {e.get('path')}"
            elif kind == "recovery":
                desc = (f"{e.get('fault')}/{e.get('action')} from round "
                        f"{e.get('from_round')}")
            elif kind == "fault":
                desc = (f"{e.get('injected')} injected at round "
                        f"{e.get('round')} ({e.get('phase')})")
            else:
                desc = e.get("fn", "")
            print(f"  {kind:<16} {desc}")
    return 0


def report_slo(path: str, run_id: str | None = None) -> int:
    """Render the SLO stream (ISSUE 17): the last ``slo_report``'s
    per-(cohort, tenant) phase-attribution table, the per-objective
    error-budget timeline across every report, and the alert /
    autoscale trails.  Stdlib-only like everything in this script."""
    reports: list = []
    alerts: list = []
    signals: list = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if run_id is not None and rec.get("run_id") != run_id:
                continue
            if rec.get("event") == "slo_report":
                reports.append(rec)
            elif rec.get("event") == "slo_alert":
                alerts.append(rec)
            elif rec.get("event") == "autoscale_signal":
                signals.append(rec)
    if not reports:
        which = f" for run {run_id!r}" if run_id else ""
        print(
            f"(no slo_report record{which} in {path} — was the service "
            f"run with an SLO policy and a file-backed metrics sink?)",
            file=sys.stderr,
        )
        return 1
    # The attribution quantiles are PER-REPORT-WINDOW deltas; the last
    # report of a drained service usually saw an empty window.  Render
    # the freshest report that actually observed traffic.
    last = reports[-1]
    for rep in reversed(reports):
        if any(g.get("window_events") for g in rep.get("groups", [])):
            last = rep
            break
    print(f"== slo attribution ({last.get('run_id')}) ==")
    print(
        f"  {'cohort':<26} {'tenant':<10} {'ok':>5} {'exp':>5} "
        f"{'rej':>5} {'fail':>5} {'wall p99':>10} {'dominant phase':>22}"
    )
    phase_names = (
        "queue_s", "coalesce_s", "compile_s", "dispatch_s", "retire_lag_s"
    )
    for g in last.get("groups", []):
        phases = g.get("phases", {})
        p99s = {
            n: (phases.get(n, {}).get("p99") or 0.0) for n in phase_names
        }
        dom = max(p99s, key=p99s.get) if any(p99s.values()) else "-"
        wall = phases.get("wall_s", {}).get("p99")
        counts = g.get("counts", {})
        print(
            f"  {g.get('cohort', '?'):<26} {g.get('tenant', '?'):<10} "
            f"{counts.get('ok', 0):>5} {counts.get('expired', 0):>5} "
            f"{counts.get('rejected', 0):>5} {counts.get('failed', 0):>5} "
            f"{_fmt_s(wall) if wall is not None else '-':>10} "
            f"{dom + ' ' + _fmt_s(p99s[dom]) if dom != '-' else '-':>22}"
        )
        bad = g.get("attribution_bad", 0)
        if bad:
            print(
                f"    !! {bad}/{g.get('attribution_checked')} requests "
                f"failed sum(phases) ~= wall"
            )
    print("== error-budget timeline ==")
    print(
        f"  {'ts':>14} {'objective':<16} {'burn':>8} {'fast':>8} "
        f"{'slow':>8} {'budget':>8} {'alert':>6}"
    )
    for rep in reports:
        ts = rep.get("ts")
        for o in rep.get("objectives", []):
            print(
                f"  {ts if ts is not None else '-':>14} "
                f"{o.get('name', '?'):<16} "
                f"{o.get('burn') if o.get('burn') is not None else '-':>8} "
                f"{o.get('burn_fast') if o.get('burn_fast') is not None else '-':>8} "
                f"{o.get('burn_slow') if o.get('burn_slow') is not None else '-':>8} "
                f"{o.get('budget_remaining') if o.get('budget_remaining') is not None else '-':>8} "
                f"{'FIRE' if o.get('alerting') else 'ok':>6}"
            )
    if alerts:
        print("== alerts ==")
        for a in alerts:
            print(
                f"  {a.get('ts', '-'):>14} {a.get('objective'):<16} "
                f"{a.get('state'):<6} fast={a.get('burn_fast')} "
                f"slow={a.get('burn_slow')} threshold={a.get('threshold')}"
            )
    if signals:
        print("== autoscale signals ==")
        for s in signals[-10:]:
            print(
                f"  {s.get('ts', '-'):>14} replicas {s.get('replicas')} "
                f"-> {s.get('recommended')} ({s.get('reason')}; "
                f"queue_frac={s.get('queue_frac')} burn={s.get('burn')})"
            )
    return 0


def report_fleet(path: str) -> int:
    """Render a sink DIRECTORY (ISSUE 19 ``BA_TPU_METRICS=dir/`` mode)
    as a fleet summary: the shard census with clock anchors, the
    merged per-request table (wall vs phase-attribution sum, trace
    span/process fan-out), the pool-task offload tally and a cohort
    rollup.  Self-aggregates like ``report_slo`` — stdlib only, no
    ba_tpu import (this script must run anywhere the shards were
    copied to); ``python -m ba_tpu.obs.fleet DIR`` does the full
    span-tree assembly with fan-in grafting."""
    import re

    shard_re = re.compile(r"^(\d+)\.(.+)\.jsonl$")
    try:
        names = sorted(n for n in os.listdir(path) if shard_re.match(n))
    except OSError as e:
        print(f"(cannot list {path}: {e})", file=sys.stderr)
        return 1
    if not names:
        print(f"(no <pid>.<token>.jsonl shards in {path} — was the "
              f"session run with BA_TPU_METRICS set to a directory?)",
              file=sys.stderr)
        return 1
    merged: list = []
    census: list = []
    for name in names:
        offset = None
        recs = []
        with open(os.path.join(path, name)) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail: skip, never fatal
                if rec.get("event") == "clock_anchor":
                    offset = rec.get("ts", 0.0) - rec.get("perf_t", 0.0)
                recs.append(rec)
        for i, rec in enumerate(recs):
            t_perf = rec.get("t_perf")
            if t_perf is not None and offset is not None:
                t = t_perf + offset
            else:
                t = rec.get("ts") or 0.0
            merged.append((round(t, 6), name, i, rec))
        census.append((name, int(shard_re.match(name).group(1)),
                       len(recs), offset))
    merged.sort(key=lambda e: e[:3])
    records = [rec for _, _, _, rec in merged]

    print(f"== fleet shards ({path}) ==")
    print(f"  {'shard':<36} {'pid':>8} {'records':>8} {'anchored':>9}")
    for name, pid, n, offset in census:
        print(f"  {name:<36} {pid:>8} {n:>8} "
              f"{'yes' if offset is not None else 'NO':>9}")

    spans = {}
    parents = 0
    unresolved = 0
    external = 0
    for rec in records:
        sid = rec.get("span_id")
        if sid:
            spans.setdefault(sid, rec.get("trace_id"))
    for rec in records:
        pid_ = rec.get("parent_id")
        if rec.get("span_id") and pid_ is not None:
            parents += 1
            if pid_ not in spans:
                # A missing parent on an ADOPTION root (a request, or a
                # zero-duration inject_scope mark) is the caller's
                # injected traceparent — external by construction, not
                # breakage.  Anything else lost its in-stream parent.
                if rec.get("event") == "request" or (
                    rec.get("event") == "trace_span"
                    and rec.get("dur_s") == 0
                ):
                    external += 1
                else:
                    unresolved += 1

    requests = [r for r in records if r.get("event") == "request"]
    phase_names = (
        "queue_s", "coalesce_s", "compile_s", "dispatch_s", "retire_lag_s"
    )
    if requests:
        print("== requests ==")
        print(f"  {'id':>4} {'cohort':<26} {'tenant':<10} {'status':<8} "
              f"{'wall':>10} {'attrib':>10} {'tol':>4} {'spans':>6}")
        by_trace: dict = {}
        for sid, tid in spans.items():
            if tid:
                by_trace[tid] = by_trace.get(tid, 0) + 1
        for r in requests:
            phases = [r.get(k) for k in phase_names]
            attrib = (sum(phases)
                      if all(isinstance(p, (int, float)) for p in phases)
                      else None)
            wall = r.get("wall_s")
            within = (attrib is not None
                      and isinstance(wall, (int, float))
                      and abs(attrib - wall) <= 2e-3)
            print(
                f"  {r.get('id', '?'):>4} {r.get('cohort', '?'):<26} "
                f"{(r.get('tenant') or '-'):<10} {r.get('status', '?'):<8} "
                f"{_fmt_s(wall) if wall is not None else '-':>10} "
                f"{_fmt_s(attrib) if attrib is not None else '-':>10} "
                f"{'ok' if within else 'BAD':>4} "
                f"{by_trace.get(r.get('trace_id'), 0):>6}"
            )
    pool_tasks = [r for r in records if r.get("event") == "pool_task"]
    if pool_tasks:
        print("== pool offload ==")
        kinds: dict = {}
        for r in pool_tasks:
            k = r.get("kind", "?")
            rows, wall = kinds.get(k, (0, 0.0))
            kinds[k] = (rows + (r.get("rows") or 0),
                        wall + (r.get("wall_s") or 0.0))
        for k, (rows, wall) in sorted(kinds.items()):
            print(f"  {k:<10} {rows:>6} rows  {_fmt_s(wall):>10} total")
    cohorts: dict = {}
    for r in requests:
        cohorts.setdefault(r.get("cohort", "?"), []).append(r)
    if cohorts:
        print("== cohorts ==")
        print(f"  {'cohort':<26} {'requests':>8} {'ok':>5} {'p99 wall':>10}")
        for name, rs in sorted(cohorts.items()):
            walls = sorted(
                r["wall_s"] for r in rs
                if isinstance(r.get("wall_s"), (int, float))
            )
            p99 = walls[max(0, int(0.99 * len(walls)) - 1)] if walls else None
            ok = sum(1 for r in rs if r.get("status") == "ok")
            print(f"  {name:<26} {len(rs):>8} {ok:>5} "
                  f"{_fmt_s(p99) if p99 is not None else '-':>10}")
    print(f"== parenting ==")
    print(f"  spans {len(spans)}  child-edges {parents}  "
          f"external roots {external}  "
          f"unresolved parents {unresolved}")
    return 1 if unresolved else 0


def report_metrics(path: str) -> None:
    events: dict = {}
    snapshot = None
    artifacts: list = []
    recompiles: list = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            events[rec.get("event", "?")] = events.get(rec.get("event", "?"), 0) + 1
            if rec.get("event") == "metrics_snapshot":
                snapshot = rec
            elif rec.get("event") == "compiled_artifact":
                artifacts.append(rec)
            elif rec.get("event") == "recompile":
                recompiles.append(rec)
    print(f"== JSONL events ({path}) ==")
    for name, c in sorted(events.items()):
        print(f"  {name:<32} {c:>6}")
    report_device(artifacts, recompiles)
    if snapshot is None:
        print("  (no metrics_snapshot record)")
        return
    metrics = snapshot.get("metrics", {})
    scalars = {
        k: v for k, v in metrics.items() if v["type"] in ("counter", "gauge")
    }
    if scalars:
        print("== counters / gauges ==")
        for name, v in sorted(scalars.items()):
            print(f"  {name:<32} {v['value']:>12}")
    hists = {k: v for k, v in metrics.items() if v["type"] == "histogram"}
    if hists:
        print("== histograms ==")
        print(
            f"  {'name':<32} {'count':>6} {'mean':>12} {'p50<=':>12} "
            f"{'p90<=':>12} {'max':>12}"
        )
        for name, h in sorted(hists.items()):
            count = h["count"]
            mean = h["sum"] / count if count else 0.0
            p50 = _hist_quantile(h["buckets"], count, 0.5)
            p90 = _hist_quantile(h["buckets"], count, 0.9)
            time_like = name.endswith("_s")
            fmt = _fmt_s if time_like else (lambda x: f"{x:g}")
            print(
                f"  {name:<32} {count:>6} "
                f"{fmt(mean) if count else '-':>12} "
                f"{fmt(p50) if p50 is not None else '-':>12} "
                f"{fmt(p90) if p90 is not None else '-':>12} "
                f"{fmt(h['max']) if h['max'] is not None else '-':>12}"
            )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dir", nargs="?", help="bench.py --obs output directory")
    ap.add_argument("--trace", help="Chrome trace-event JSON path")
    ap.add_argument("--metrics", help="metrics JSONL path")
    ap.add_argument("--flight", action="store_true",
                    help="render the assembled flight_summary (ISSUE 9) "
                         "from the metrics JSONL instead of the span/"
                         "metrics tables")
    ap.add_argument("--run-id", default=None,
                    help="which run's flight to render (default: the "
                         "stream's last flight_summary)")
    ap.add_argument("--slo", action="store_true",
                    help="render the SLO stream (ISSUE 17): phase "
                         "attribution table, error-budget timeline, "
                         "alert + autoscale trails")
    ap.add_argument("--fleet", action="store_true",
                    help="render a sharded sink DIRECTORY (ISSUE 19 "
                         "BA_TPU_METRICS=dir/ mode): shard census with "
                         "clock anchors, merged per-request attribution "
                         "table, pool offload + cohort rollup")
    args = ap.parse_args()
    if args.fleet:
        target = args.dir or args.metrics
        if not target:
            ap.error("--fleet takes the sink DIRECTORY (positional or "
                     "--metrics)")
        return report_fleet(target)
    trace, metrics = args.trace, args.metrics
    if args.dir:
        trace = trace or os.path.join(args.dir, "trace.json")
        metrics = metrics or os.path.join(args.dir, "metrics.jsonl")
    if not trace and not metrics:
        ap.error("give DIR or --trace/--metrics")
    if args.flight:
        if not metrics or not os.path.exists(metrics):
            print(f"(missing: {metrics})", file=sys.stderr)
            return 1
        return report_flight(metrics, run_id=args.run_id)
    if args.slo:
        if not metrics or not os.path.exists(metrics):
            print(f"(missing: {metrics})", file=sys.stderr)
            return 1
        return report_slo(metrics, run_id=args.run_id)
    found = False
    for path, render in ((trace, report_trace), (metrics, report_metrics)):
        if path and os.path.exists(path):
            render(path)
            found = True
        elif path:
            print(f"(missing: {path})", file=sys.stderr)
    return 0 if found else 1


if __name__ == "__main__":
    sys.exit(main())
