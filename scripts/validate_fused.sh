#!/usr/bin/env bash
# Hardware validation + same-window A/B for the fused sweep kernel.
# Run ALONE (one TPU chip, one claim).  Produces:
#   TESTS_TPU_FUSED_r{N}.txt  - the kernel's differential tests on chip
#   SWEEP_STAGES_r{N}.json    - per-stage breakdown of the XLA sweep step
#   FUSED_AB_r{N}.json        - same-window XLA-vs-fused sweep bench A/B
set -u
N="${1:?usage: validate_fused.sh <round>}"
cd "$(dirname "$0")/.."

echo "== fused kernel differential tests (first Mosaic compile included)"
BA_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_ops.py -q -k "fused" \
    > "TESTS_TPU_FUSED_r${N}.txt" 2>&1
rc_tests=$?
tail -2 "TESTS_TPU_FUSED_r${N}.txt"
[ $rc_tests -ne 0 ] && { echo "TESTS FAILED - stopping"; exit 1; }

echo "== sweep stage breakdown (XLA path)"
python scripts/sweep_stages.py > "SWEEP_STAGES_r${N}.json" 2> /tmp/sweep_stages.err
tail -c 400 "SWEEP_STAGES_r${N}.json"; echo

echo "== same-window A/B: XLA vs fused sweep config"
# bench.py's stdout is the compact headline line; the per-config detail
# lands in the BA_TPU_BENCH_DETAIL file (bench.py output contract, r4).
# Stale files are removed first and each run must succeed, so a crashed
# bench can never silently pair one side with a previous run's numbers.
rm -f /tmp/fused_ab_xla.json /tmp/fused_ab_fused.json
BA_TPU_FUSED_SWEEP=0 BA_TPU_BENCH_DETAIL=/tmp/fused_ab_xla.json \
    python bench.py --configs sweep10k_signed > /dev/null \
    2> /tmp/fused_ab_xla.err || { echo "XLA bench failed"; exit 1; }
BA_TPU_FUSED_SWEEP=1 BA_TPU_BENCH_DETAIL=/tmp/fused_ab_fused.json \
    python bench.py --configs sweep10k_signed > /dev/null \
    2> /tmp/fused_ab_fused.err || { echo "fused bench failed"; exit 1; }
python - /tmp/fused_ab_xla.json /tmp/fused_ab_fused.json \
    > "FUSED_AB_r${N}.json" <<'EOF'
import json, sys
xla = json.load(open(sys.argv[1]))["configs"]["sweep10k_signed"]
fused = json.load(open(sys.argv[2]))["configs"]["sweep10k_signed"]
out = {
    "metric": "fused-sweep-ab",
    "xla": {k: xla[k] for k in ("rounds_per_sec", "elapsed_s",
                                "incl_setup_crossover_1M_iters")},
    "fused": {k: fused[k] for k in ("rounds_per_sec", "elapsed_s",
                                    "incl_setup_crossover_1M_iters",
                                    "fused_rounds_per_dispatch")},
    # rounds/s ratio, NOT elapsed ratio: the fused step dispatches
    # fused_rounds_per_dispatch rounds per iteration, the XLA step one.
    "speedup_fused": round(
        fused["rounds_per_sec"] / xla["rounds_per_sec"], 3
    ),
}
print(json.dumps(out))
EOF
cat "FUSED_AB_r${N}.json"
