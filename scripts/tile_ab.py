"""Same-window tile-size A/B for the fused sweep kernel (one process,
interleaved reps so service drift cancels).  Run ALONE.

TILE_AB_TILES picks the tile candidates; TILE_AB_ROUNDS sets the fused
rounds-per-dispatch K the tiles are compared at (the production default
should be A/B'd at the production K)."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ab_common import emit, interleaved_ab, sweep_fixture
    from ba_tpu.ops.sweep_step import fused_signed_sweep_step

    tiles = [int(t) for t in
             os.environ.get("TILE_AB_TILES", "32,64,128,256").split(",")]
    k_rounds = int(os.environ.get("TILE_AB_ROUNDS", 1))
    batch, m = 10240, 3
    iters, reps = 30, 3
    states, oks = sweep_fixture(batch)

    def make_step(tile):
        @jax.jit
        def step(seed):
            acc = jnp.int32(0)
            for i, (st, okb) in enumerate(zip(states, oks)):
                dec = fused_signed_sweep_step(
                    seed + i, st.order, st.leader, st.faulty, st.alive,
                    okb, m, k_rounds, tile=tile,
                )
                acc += dec.astype(jnp.int32).sum()
            return acc
        return step

    best = interleaved_ab({t: make_step(t) for t in tiles}, iters, reps)
    emit(
        "fused-tile-ab", batch, iters,
        {
            str(t): (
                {"error": "compile-failed (see stderr)"}
                if e == float("inf")
                else {"elapsed_s": round(e, 4),
                      "rounds_per_sec": round(batch * k_rounds * iters / e, 1)}
            )
            for t, e in best.items()
        },
        rounds_per_dispatch=k_rounds,
    )


if __name__ == "__main__":
    main()
