"""Same-window tile-size A/B for the fused sweep kernel (one process,
interleaved reps so service drift cancels).  Run ALONE."""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import bucketed_sweep_states

    tiles = [int(t) for t in
             os.environ.get("TILE_AB_TILES", "32,64,128,256").split(",")]
    batch, cap, m = 10240, 1024, 3
    iters, reps = 30, 3
    states = bucketed_sweep_states(jr.key(5), batch, cap, 2)
    ok = jnp.ones((batch, 2), bool)
    oks, off = [], 0
    for s in states:
        b = s.faulty.shape[0]
        oks.append(ok[off:off + b])
        off += b

    def make_step(tile):
        @jax.jit
        def step(seed):
            acc = jnp.int32(0)
            for i, (st, okb) in enumerate(zip(states, oks)):
                dec = fused_signed_sweep_step(
                    seed + i, st.order, st.leader, st.faulty, st.alive,
                    okb, m, tile=tile,
                )
                acc += dec.astype(jnp.int32).sum()
            return acc
        return step

    from bench import _timed  # the tunnel-safe timing single source of truth

    steps = {t: make_step(t) for t in tiles}
    for t, step in steps.items():  # compile + warm, off the clock
        jax.device_get(step(jnp.asarray([t], jnp.int32)))

    best = {t: float("inf") for t in tiles}
    for r in range(reps):  # interleave tiles within each rep: drift cancels
        for t, step in steps.items():
            mk = lambda i, _r=r: (jnp.asarray([_r * 1000 + i], jnp.int32),)
            best[t] = min(best[t], _timed(steps[t], mk, iters, reps=1))

    out = {
        "metric": "fused-tile-ab", "batch": batch, "iters": iters,
        "tiles": {
            str(t): {"elapsed_s": round(e, 4),
                     "rounds_per_sec": round(batch * iters / e, 1)}
            for t, e in best.items()
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
