#!/usr/bin/env python
"""Span-budget audit: tracer-overhead A/B on the `run-rounds` path.

PR 2's open ROADMAP item: a span costs two ``perf_counter_ns`` reads
plus a deque append — confirm the trace-enabled ``run-rounds`` path
shows no measurable regression and record the number in the BENCH
series.  This harness runs the REAL path (``Cluster`` →
``JaxBackend.run_rounds`` → the pipelined sweep engine, spans on every
dispatch/retire/host_work plus the per-dispatch ``pipeline_dispatch``
sink records) with the tracer ENABLED vs DISABLED, reps interleaved so
both sides share one service window, and prints one JSON line:

    JAX_PLATFORMS=cpu python scripts/span_budget_ab.py > BENCH_span_budget_rN.json

Knobs: ``BA_TPU_SPAN_AB_ROUNDS`` (default 64 rounds per rep),
``BA_TPU_SPAN_AB_REPS`` (default 5, min-of-reps per side),
``BA_TPU_SPAN_AB_PLATFORM`` (default cpu; set tpu on the tunnel for the
dispatch-scale number the ROADMAP asks about).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from ba_tpu import obs
    from ba_tpu.obs.registry import MetricsRegistry
    from ba_tpu.obs.trace import Tracer
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster

    platform = os.environ.get("BA_TPU_SPAN_AB_PLATFORM", "cpu")
    rounds = int(os.environ.get("BA_TPU_SPAN_AB_ROUNDS", 64))
    reps = int(os.environ.get("BA_TPU_SPAN_AB_REPS", 5))

    cluster = Cluster(4, JaxBackend(platform=platform), seed=0)
    cluster.set_faulty(3, True)
    # Warm: compile the megastep + the last-round majority recompute off
    # the clock (both sides reuse the same jit cache afterwards).
    cluster.actual_order_rounds("attack", rounds)

    def run_side(enabled: bool) -> tuple[float, int]:
        # A fresh tracer/registry per timed run: the enabled side pays
        # the REAL record/append cost, the disabled side the enabled
        # check only — exactly the production toggle (BA_TPU_TRACE).
        # Returns (elapsed seconds, spans recorded).
        obs.trace._default = Tracer(enabled=enabled)
        obs.registry._default = MetricsRegistry()
        t0 = time.perf_counter()
        cluster.actual_order_rounds("attack", rounds)
        elapsed = time.perf_counter() - t0
        spans = len(obs.default_tracer())
        return elapsed, spans

    t_on = t_off = float("inf")
    spans_per_run = 0
    for _ in range(reps):  # interleaved: window drift cancels
        e_on, spans_per_run = run_side(True)
        t_on = min(t_on, e_on)
        e_off, _ = run_side(False)
        t_off = min(t_off, e_off)

    overhead_s = t_on - t_off
    line = {
        "metric": "span-budget",
        "platform": platform,
        "path": "Cluster.actual_order_rounds (pipelined run-rounds)",
        "rounds_per_run": rounds,
        "reps": reps,
        "span_on_s": round(t_on, 6),
        "span_off_s": round(t_off, 6),
        "overhead_s": round(overhead_s, 6),
        "overhead_pct": round(100 * overhead_s / t_off, 2),
        "spans_per_run": spans_per_run,
        "est_ns_per_span": (
            round(overhead_s / spans_per_run * 1e9, 1)
            if spans_per_run and overhead_s > 0
            else None
        ),
        "note": "min-of-reps, sides interleaved in one window; "
                "negative overhead = below measurement noise",
    }
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
