"""Same-window A/B over the overlapped key-set setup's chunk count.

chunks=1 is the r3-style sequential setup (sign everything, one verify
dispatch); higher counts overlap host signing with device verify but pay
one tunnel dispatch+upload ACK per chunk.  Which wins depends on the
window's dispatch latency, so: interleaved, min-of-reps, one process.
Run ALONE."""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    from ba_tpu.crypto.signed import (
        setup_signed_tables_overlapped,
        warm_signed_tables,
    )

    batch = int(os.environ.get("SETUP_AB_BATCH", 10240))
    chunk_counts = [int(c) for c in
                    os.environ.get("SETUP_AB_CHUNKS", "1,2,4,8").split(",")]
    reps = 3
    for c in chunk_counts:  # compile each chunk shape off the clock
        warm_signed_tables(batch, c)

    best = {c: None for c in chunk_counts}
    for r in range(reps):
        for c in chunk_counts:
            # Fresh keys per attempt (seed varies): content-distinct
            # dispatches, and keygen+signing stay on the clock as in the
            # bench's setup accounting.
            *_, t = setup_signed_tables_overlapped(
                batch, seed=1000 + r * 100 + c, chunks=c
            )
            if best[c] is None or t["total_s"] < best[c]["total_s"]:
                best[c] = t
    print(json.dumps({
        "metric": "setup-chunks-ab", "batch": batch, "reps": reps,
        "variants": {
            str(c): {k: round(v, 4) if isinstance(v, float) else v
                     for k, v in t.items()}
            for c, t in best.items()
        },
    }))


if __name__ == "__main__":
    main()
