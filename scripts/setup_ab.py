"""Same-window A/B over the overlapped key-set setup's chunk count and
(r5) its substrate modes.

chunks=1 is the r3-style sequential setup (sign everything, one verify
dispatch); higher counts overlap host signing with device verify but pay
one tunnel dispatch+upload ACK per chunk.  SETUP_AB_MODES (r5) adds the
substrate axis: comma-separated combos of ``host``/``dev`` (who signs —
BA_TPU_SIGN_DEVICE) x ``exact``/``rlc`` (how tables verify —
BA_TPU_VERIFY_RLC, the deferred-fetch route), e.g.
``host-exact,dev-exact,host-rlc,dev-rlc``.  Which wins depends on the
window's dispatch latency, so: interleaved, min-of-reps, one process.
Run ALONE."""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_KNOBS = {"host": ("BA_TPU_SIGN_DEVICE", "0"), "dev": ("BA_TPU_SIGN_DEVICE", "1"),
          "exact": ("BA_TPU_VERIFY_RLC", "0"), "rlc": ("BA_TPU_VERIFY_RLC", "1")}


def _set_mode(mode: str) -> None:
    for part in mode.split("-"):
        k, v = _KNOBS[part]
        os.environ[k] = v


def main() -> None:
    from ba_tpu.crypto.signed import (
        setup_signed_tables_overlapped,
        warm_signed_tables,
    )

    batch = int(os.environ.get("SETUP_AB_BATCH", 10240))
    chunk_counts = [int(c) for c in
                    os.environ.get("SETUP_AB_CHUNKS", "1,2,4,8").split(",")]
    modes = os.environ.get("SETUP_AB_MODES", "host-exact").split(",")
    reps = 3
    for mode in modes:  # compile every (mode, chunk shape) off the clock
        _set_mode(mode)
        for c in chunk_counts:
            warm_signed_tables(batch, c)

    best: dict[tuple[str, int], dict | None] = {
        (m, c): None for m in modes for c in chunk_counts
    }
    for r in range(reps):
        for mi, m in enumerate(modes):
            _set_mode(m)
            for c in chunk_counts:
                # Fresh keys per attempt — the seed varies with rep, MODE
                # and chunk count, so no two timed setups ever dispatch
                # byte-identical content (Ed25519 determinism would
                # otherwise make a later mode's dispatches byte-identical
                # repeats of an earlier one's from the same seed, and the
                # tunnel memoizes those); keygen+signing stay on the
                # clock as in the bench's setup accounting.
                *_, t = setup_signed_tables_overlapped(
                    batch, seed=1000 + r * 1000 + mi * 100 + c, chunks=c
                )
                key = (m, c)
                if best[key] is None or t["total_s"] < best[key]["total_s"]:
                    best[key] = t
    print(json.dumps({
        "metric": "setup-chunks-ab", "batch": batch, "reps": reps,
        "variants": {
            f"{m}/chunks={c}": {k: round(v, 4) if isinstance(v, float) else v
                                for k, v in t.items()}
            for (m, c), t in best.items()
        },
    }))


if __name__ == "__main__":
    main()
