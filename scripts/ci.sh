#!/usr/bin/env bash
# CI entry: ba-lint static analysis + the tier-1 suite (ROADMAP.md,
# verbatim).
#
# ISSUE 3 replaced the PR 1/2 text greps with `python -m
# ba_tpu.analysis` (ba-lint): a zero-dependency pure-ast analyzer that
# resolves import aliases (an `import numpy as jnp_like` no longer
# sails through), computes the real import graph, and expresses the
# donation and RNG-linearity contracts greps structurally cannot.
# Rule <-> old-grep mapping:
#
#   BA101 host-sync-in-hot-path      <- grep block 1: block_until_ready
#                                       in ba_tpu/parallel/ + host
#                                       np.asarray/np.array in
#                                       pipeline.py/sweep.py (now also
#                                       .item()/.tolist()/float()/int()
#                                       drains, alias-resolved)
#   BA102 host-key-split-in-pipeline <- grep block 2: jr.split /
#                                       random.split in pipeline.py
#                                       (now alias-resolved, plus
#                                       fold_in inside host loops)
#   BA301 obs-purity                 <- grep block 3: metrics.emit /
#                                       ba_tpu.obs / obs.span in
#                                       ba_tpu/core|ops (now the
#                                       transitive direct-import
#                                       closure, alias-resolved)
#   BA201 use-after-donate           <- new: no grep could express it
#   BA202 rng-key-reuse              <- new: no grep could express it
#   BA401 dead-import                <- new, warning-level ratchet
#   BA501-BA504 concurrency          <- new (ISSUE 18): unsynchronized
#                                       shared mutation, lock-free-read
#                                       discipline, lock-order cycles,
#                                       leaked timers/threads
#   BA601-BA603 contracts            <- new (ISSUE 18): emit sites vs
#                                       analysis/contracts.py record
#                                       registry, metric naming at
#                                       construction sites, BA_TPU_*
#                                       env reads vs the README table
#
# ba-lint never imports jax, so this stage costs seconds and runs on
# any host.  Findings output is a schema-versioned JSON object,
# validated below exactly like the metrics JSONL records are.

set -u
cd "$(dirname "$0")/.."

echo "== ba-lint static analysis: ba_tpu/ examples/ bench.py tests/ scripts/ =="
# ISSUE 4 satellite (ROADMAP open item from PR 3): the lint set now
# covers tests/ and scripts/ at error level too; the deliberately-
# violating lint fixtures are pruned via --exclude (both already ran
# clean — tests/test_analysis.py pins it — CI now gates on them).
balint_json=$(mktemp)
balint_sarif=$(mktemp)
trap 'rm -rf "$balint_json" "$balint_sarif" "${mutdir:-}"' EXIT
python -m ba_tpu.analysis ba_tpu/ examples/ bench.py tests/ scripts/ \
    --exclude tests/fixtures/ba_lint --format json \
    --sarif "$balint_sarif" \
    > "$balint_json"
balint_rc=$?
# Schema check (mirrors scripts/check_metrics_schema.py's contract for
# the metrics JSONL: every consumer-facing record parses and carries
# its schema version) + legacy stderr messaging per rule family.
python - "$balint_json" "$balint_rc" <<'EOF'
import json, sys

path, rc = sys.argv[1], int(sys.argv[2])
with open(path) as fh:
    doc = json.load(fh)
for field in ("version", "tool", "files_scanned", "rules", "findings",
              "suppressed", "counts", "exit"):
    assert field in doc, f"ba-lint JSON missing {field!r}"
assert doc["version"] == 1, f"unexpected ba-lint schema v{doc['version']}"
assert doc["tool"] == "ba-lint"
for f in doc["findings"] + doc["suppressed"]:
    for field in ("code", "severity", "path", "line", "col", "message"):
        assert field in f, f"finding missing {field!r}: {f}"
assert doc["exit"] == rc, (
    f"ba-lint exit {rc} disagrees with its own JSON ({doc['exit']})"
)

for f in doc["findings"]:
    print(f"{f['path']}:{f['line']}:{f['col']}: {f['code']} "
          f"[{f['severity']}] {f['message']}")
codes = {f["code"] for f in doc["findings"] if f["severity"] == "error"}
# Identical stderr messaging to the grep blocks this stage replaced.
if codes & {"BA101"}:
    print("LINT FAIL: host sync inside a parallel round-loop module",
          file=sys.stderr)
if codes & {"BA102"}:
    print("LINT FAIL: host key split in pipeline.py (keys must derive",
          "on device from the KeySchedule counter)", file=sys.stderr)
if codes & {"BA201", "BA202"}:
    print("LINT FAIL: donation/RNG-linearity contract violation",
          file=sys.stderr)
if codes & {"BA301"}:
    print("LINT FAIL: host-only instrumentation referenced inside a",
          "jitted module tree (ba_tpu/core or ba_tpu/ops)",
          file=sys.stderr)
if doc["counts"]["warning"]:
    # BA401 (dead-import) stays warning-level: visible, never fatal.
    print(f"ba-lint: {doc['counts']['warning']} warning(s) — see above",
          file=sys.stderr)
sys.exit(1 if codes else 0)
EOF
schema_rc=$?
if [ "$balint_rc" -ne 0 ] || [ "$schema_rc" -ne 0 ]; then
    echo "ba-lint failed" >&2
    exit 1
fi
# SARIF side-channel (ISSUE 18): the same run wrote a SARIF 2.1.0 log
# for code-scanning upload.  Validate its shape here — still jax-free,
# still sub-second (tests/test_analysis.py pins the full structure).
python - "$balint_sarif" <<'EOF'
import json, sys

with open(sys.argv[1]) as fh:
    doc = json.load(fh)
assert doc["version"] == "2.1.0", doc.get("version")
assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
(run,) = doc["runs"]
driver = run["tool"]["driver"]
assert driver["name"] == "ba-lint"
ids = {r["id"] for r in driver["rules"]}
assert {"BA101", "BA301", "BA501", "BA601"} <= ids, sorted(ids)
for res in run["results"]:
    assert res["ruleId"] in ids
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"]
    assert loc["region"]["startLine"] >= 1
print(f"ba-lint SARIF OK ({len(run['results'])} result(s), "
      f"{len(ids)} rule(s))")
EOF
if [ $? -ne 0 ]; then
    echo "ba-lint SARIF validation failed" >&2
    exit 1
fi
echo "ba-lint OK"

echo "== ba-lint mutation check =="
# Guard against the analyzer rotting into a silent no-op: seed one
# banned idiom per core rule into a tempdir copy of the tree and assert
# ba-lint goes red with the right code.  Each mutation uses an import
# alias a grep could not have followed.
mutdir=$(mktemp -d)
mutate_and_expect() {
    # $1 = rule code, $2 = target file (relative), $3 = seeded code.
    # The copy keeps its `ba_tpu` name: rules scope on the dotted
    # module name derived from __init__.py ancestry, so the tempdir
    # copy scopes identically to the real tree.
    rm -rf "$mutdir/ba_tpu"
    cp -r ba_tpu "$mutdir/ba_tpu"
    rm -rf "$mutdir/ba_tpu/analysis"   # lint the product tree, not the linter
    printf '\n%s\n' "$3" >> "$mutdir/ba_tpu/$2"
    if python -m ba_tpu.analysis "$mutdir/ba_tpu" --format json \
            > "$mutdir/out.json"; then
        echo "MUTATION CHECK FAIL: seeded $1 violation not fatal" >&2
        return 1
    fi
    if ! grep -q "\"code\": \"$1\"" "$mutdir/out.json"; then
        echo "MUTATION CHECK FAIL: $1 missing from findings JSON" >&2
        return 1
    fi
    echo "mutation check OK: seeded $1 goes red"
}
mutate_and_expect BA101 parallel/pipeline.py \
    'def _mut101(x):
    return x.block_until_ready()' || exit 1
mutate_and_expect BA102 parallel/pipeline.py \
    'import jax.random as _mut_jr
def _mut102(key):
    return _mut_jr.split(key)' || exit 1
mutate_and_expect BA301 core/om.py \
    'from ba_tpu import obs as _mut_obs' || exit 1
# ISSUE 8: the mesh scan core (parallel/shard.py) joined the BA101
# hot-path scope — prove the extension is live, not just declared.
mutate_and_expect BA101 parallel/shard.py \
    'def _mut101_shard(x):
    return x.block_until_ready()' || exit 1
# ISSUE 13: the Pallas scenario megastep (ops/scenario_step.py) is the
# dispatch path when the kernel engine is selected and joined the
# BA101 hot-path scope — prove that extension is live too.
mutate_and_expect BA101 ops/scenario_step.py \
    'def _mut101_megastep(x):
    return x.block_until_ready()' || exit 1
# ISSUE 14: the sign-ahead host lane (parallel/signing.py) is a NEW
# module inside the BA101 hot-path scope (ba_tpu.parallel.*) — its job
# is host work in the overlap slot, but a block_until_ready there would
# serialize the lane against the in-flight dispatches it exists to
# overlap.  Prove the scope covers it.
mutate_and_expect BA101 parallel/signing.py \
    'def _mut101_signing(x):
    return x.block_until_ready()' || exit 1
# ISSUE 16: the host-crypto pool (crypto/pool.py) joined the BA101
# hot-path scope — SignAheadLane calls it inside the engine's overlap
# slot, where a device sync would block the dispatch loop (and the
# module is jax-free by contract besides).  Prove the scope covers it.
mutate_and_expect BA101 crypto/pool.py \
    'def _mut101_pool(x):
    return x.block_until_ready()' || exit 1
# ISSUE 9: BA301 grew the symmetric host-tier scope — obs modules
# (the flight recorder and health sampler in particular) must never
# import through ba_tpu.core/ba_tpu.ops.  Prove the direction is live.
mutate_and_expect BA301 obs/flight.py \
    'from ba_tpu.core import om as _mut_core' || exit 1
mutate_and_expect BA301 obs/health.py \
    'from ba_tpu.ops import sweep_step as _mut_ops' || exit 1
# ...including INDIRECTLY: an obs module pulling a host-layer module
# whose own closure reaches core (parallel.sweep -> core.*) is the
# likelier real-world breach.
mutate_and_expect BA301 obs/health.py \
    'from ba_tpu.parallel import sweep as _mut_indirect' || exit 1
# ISSUE 10: the serving front-end joined the host-tier scope at MODULE
# level — `import ba_tpu.runtime.serve` must never pull the jitted
# trees (admission control and plan validation run jax-free; the
# dispatcher reaches the engine through function-local imports).
# Prove both directions are live: a direct core import and an indirect
# one through the engine.
mutate_and_expect BA301 runtime/serve.py \
    'from ba_tpu.core import om as _mut_core' || exit 1
mutate_and_expect BA301 runtime/serve.py \
    'from ba_tpu.parallel import pipeline as _mut_engine' || exit 1
# ISSUE 11: the warmup pass joined the module-level host-tier scope
# (plan construction runs jax-free; AOT builders load lazily from the
# runner thread), and the executable cache is an obs module — the
# STRICTER obs rule covers even function-local core imports there.
# Prove both extensions are live.
mutate_and_expect BA301 runtime/warmup.py \
    'from ba_tpu.core import om as _mut_core' || exit 1
mutate_and_expect BA301 runtime/warmup.py \
    'from ba_tpu.parallel import pipeline as _mut_engine' || exit 1
mutate_and_expect BA301 obs/aotcache.py \
    'from ba_tpu.core import om as _mut_core' || exit 1
# ISSUE 17: the SLO engine is an obs module — the STRICTER obs rule
# (even function-local core/ops imports are breaches) covers it
# automatically via the ba_tpu.obs.* scope.  Prove the coverage is
# live, not just inherited on paper.
mutate_and_expect BA301 obs/slo.py \
    'from ba_tpu.core import om as _mut_core' || exit 1
# ISSUE 19: the fleet aggregator (obs/fleet.py) is an obs module — the
# STRICTER obs rule covers it via the ba_tpu.obs.* scope (it merges
# OFFLINE shard streams and must stay importable jax-free for the CI
# assembly stage below).  Prove the closure is live.  No BA501 seed:
# this PR added NO threads — trace context rides the existing emit
# paths by design (the zero-added-sync contract).
mutate_and_expect BA301 obs/fleet.py \
    'from ba_tpu.core import om as _mut_core' || exit 1
# ISSUE 15: the adversary search loop (search/loop.py) joined the BA101
# hot-path scope — its generation loop drives the coalesced engine's
# dispatch stream, and a host sync there would serialize population
# evaluation.  Prove the extension is live.
mutate_and_expect BA101 search/loop.py \
    'def _mut101_search(x):
    return x.block_until_ready()' || exit 1
# ...and the search package is host-tier at module level (the jax-free
# CLI / CI corpus stage depend on it) — prove that direction too.
mutate_and_expect BA301 search/generate.py \
    'from ba_tpu.core import om as _mut_core' || exit 1
# ISSUE 20: the fleet tier joined the module-level host-tier scope — a
# router host needs no accelerator, so `import ba_tpu.fleet.router`
# must never pull the jitted trees (the engine is reached only inside
# a replica's campaign lane, function-locally).  Prove both directions:
# a direct core import and the likelier indirect breach through the
# engine (parallel.pipeline is NOT itself host-tier, so the closure
# walk must still flag it).
mutate_and_expect BA301 fleet/router.py \
    'from ba_tpu.core import om as _mut_core' || exit 1
mutate_and_expect BA301 fleet/router.py \
    'from ba_tpu.parallel import pipeline as _mut_engine' || exit 1
mutate_and_expect BA301 fleet/migrate.py \
    'from ba_tpu.ops import sweep_step as _mut_ops' || exit 1
# ...and BA501's thread-entry discovery covers the fleet's campaign
# lanes (replica.py is thread-dense: boot threads, lane threads, drain
# events) — prove a raced attribute between a lane entry and a public
# method seeds red there too.
mutate_and_expect BA501 fleet/replica.py \
    'import threading as _mut_th
class _Mut501Fleet:
    def __init__(self):
        self._t = _mut_th.Thread(target=self._lane, daemon=True)
        self._t.start()
    def _lane(self):
        self.n = 1
    def poke(self):
        self.n = 2' || exit 1
# ISSUE 18: one seed per NEW rule family.  BA501 — a thread entry and a
# public method both write the same attribute with no common lock (the
# exact shape of the serve-tier race this PR fixed with _tier_lock).
mutate_and_expect BA501 runtime/serve.py \
    'import threading as _mut_th
class _Mut501:
    def __init__(self):
        self._t = _mut_th.Thread(target=self._loop, daemon=True)
        self._t.start()
    def _loop(self):
        self.n = 1
    def poke(self):
        self.n = 2' || exit 1
# BA601 — a versioned record of an UNDECLARED family: the emit-site
# discriminator ("event" + "v" literal keys) must catch it even as a
# bare payload, before it ever reaches a sink.
mutate_and_expect BA601 obs/flight.py \
    '_MUT601 = {"event": "mystery_event", "v": 1}' || exit 1
# BA602 — the ISSUE-required misnamed gauge: "serve" mentioned mid-name
# without the serve_ prefix must seed CI red at the CONSTRUCTION site
# (the runtime assert only fires if the line executes).
mutate_and_expect BA602 obs/slo.py \
    'def _mut602(reg):
    return reg.gauge("depth_serve_live")' || exit 1
# BA603 — an aliased read of an env knob with no README row (alias
# proves the resolver, not a grep, is doing the matching).
mutate_and_expect BA603 runtime/serve.py \
    'import os as _mut_os
_MUT603 = _mut_os.environ.get("BA_TPU_TOTALLY_UNDOCUMENTED", "")' || exit 1

echo "== scenario spec round-trip =="
# ISSUE 5: the committed campaign specs must load, validate, round-trip
# through to_dict/from_dict, and lower through the compiler.  The
# validator is jax-free by construction (spec + compiler are
# numpy/stdlib only — tests/test_scenario.py pins the no-jax property),
# so like ba-lint this stage costs well under a second.
# ISSUE 15: the search-found minimal reproducers in
# examples/scenarios/found/ are ordinary scenario specs and ride the
# same jax-free round-trip stage.
if ! python -m ba_tpu.scenario examples/scenarios/*.json \
        examples/scenarios/found/*.json; then
    echo "scenario spec validation failed" >&2
    exit 1
fi
# Their search-specific contract — a well-formed provenance.search
# replay recipe on every reproducer — is the search CLI's corpus
# check, jax-free by construction (subprocess-pinned in
# tests/test_search.py).
if ! python -m ba_tpu.search corpus examples/scenarios/found; then
    echo "search corpus validation failed" >&2
    exit 1
fi

echo "== SLO policy round-trip (jax-free) =="
# ISSUE 17: the committed SLO policy must load, eagerly validate, and
# round-trip exactly through to_doc/from_doc — `python -m
# ba_tpu.obs.slo` is jax-free by construction (subprocess-pinned in
# tests/test_slo.py), so this mirrors the scenario/chaos stages above
# at the same sub-second cost.
if ! python -m ba_tpu.obs.slo validate examples/slo/*.json; then
    echo "SLO policy validation failed" >&2
    exit 1
fi

echo "== fleet trace assembly (jax-free) =="
# ISSUE 19: the committed fixture shards (a real pooled signed serve
# session captured in sink-directory mode — two processes, three
# requests) must merge deterministically and assemble into fully-
# parented request traces whose critical-path hop sums telescope to
# the wall.  `python -m ba_tpu.obs.fleet` is jax-free by construction
# (pinned by tests/test_fleet.py), so this stage costs milliseconds —
# it exits non-zero on a nondeterministic merge, an unparented span,
# an out-of-tolerance attribution or zero assembled traces.
if ! python -m ba_tpu.obs.fleet tests/fixtures/fleet; then
    echo "fleet trace assembly failed" >&2
    exit 1
fi

echo "== chaos smoke: fault plans + fast fault-injection tests =="
# ISSUE 7: the committed fault plans must load, eagerly validate, and
# round-trip exactly through to_dict/from_dict — `python -m
# ba_tpu.runtime.chaos` is jax-free by construction (pinned by
# tests/test_supervisor.py::test_chaos_cli_jax_free_subprocess), so
# this mirrors the scenario stage above at the same sub-second cost.
if ! python -m ba_tpu.runtime.chaos examples/faults/*.json; then
    echo "fault plan validation failed" >&2
    exit 1
fi
# The fast fault-injection unit layer (classification, backoff jitter,
# watchdog derivation, plan grammar) — seconds, no engine runs; the
# full supervised-parity / kill-recovery tests run in tier-1 below.
if ! JAX_PLATFORMS=cpu python -m pytest tests/test_supervisor.py -q \
        -k "classify or backoff or derive_timeout or fault_plan or chaos_cli" \
        -p no:cacheprovider; then
    echo "chaos smoke tests failed" >&2
    exit 1
fi

echo "== serve smoke: jax-free admission layer + fast serve tests =="
# ISSUE 10: the serving front-end's admission machinery — request
# validation, shed-tier ladder, bounded-queue rejection, deadline
# bookkeeping, client-tier fault plans — runs WITHOUT jax (the module
# is host-tier by the BA301 contract proven above; the jax-free import
# is pinned by tests/test_serve.py::test_serve_import_is_jax_free).
# The engine-touching serve tests (coalesced parity, cohort isolation)
# run in tier-1 below.
if ! JAX_PLATFORMS=cpu python -m pytest tests/test_serve.py -q \
        -k "tier or admission or validate or plan or ticket or jax_free" \
        -p no:cacheprovider; then
    echo "serve smoke tests failed" >&2
    exit 1
fi

echo "== mesh parity (forced 8-device host platform) =="
# ISSUE 8: the sharded engine's bit-exactness, counter tree-reduction
# and no-blocking proofs on a live 8x1 mesh, pinned under the exact XLA
# flag tests/multihost_worker.py uses.  tests/conftest.py forces 8
# virtual devices for tier-1 anyway; this stage keeps the mesh contract
# pinned even if that default ever moves.
if ! XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        JAX_PLATFORMS=cpu python -m pytest \
        tests/test_pipeline.py tests/test_scenario.py tests/test_parallel.py \
        -q -k "mesh" -p no:cacheprovider; then
    echo "mesh parity tests failed" >&2
    exit 1
fi

echo "== bench trajectory index (jax-free) =="
# ISSUE 9: every committed BENCH_*/MULTICHIP_* artifact must still
# normalize into the sentinel's trajectory table (an artifact whose
# shape drifted out of the indexer would silently fall out of the
# regression baseline set).  Stdlib-only — sub-second, any host.
if ! python scripts/bench_sentinel.py --index-only; then
    echo "bench trajectory index failed" >&2
    exit 1
fi
# The full perf-regression sentinel runs a REAL bench.py rep and
# compares against the newest committed baseline per config — minutes
# of wall clock.  DEFAULT-ON since ISSUE 14: BENCH_trajectory.json now
# carries 40+ indexed rows of CPU baselines, so the trajectory gate
# has teeth on the CI platform — export BA_TPU_CI_SENTINEL=0 to opt a
# constrained host out (BA_TPU_CI_SENTINEL_CONFIGS narrows the config
# list).
if [ "${BA_TPU_CI_SENTINEL:-1}" = "1" ]; then
    echo "== perf-regression sentinel (default-on; BA_TPU_CI_SENTINEL=0 opts out) =="
    if ! python scripts/bench_sentinel.py --run \
            --configs "${BA_TPU_CI_SENTINEL_CONFIGS:-pipeline_sweep,scenario_sweep}"; then
        echo "perf-regression sentinel failed" >&2
        exit 1
    fi
fi

echo "== metrics JSONL schema check =="
# Every record the layer emits must parse and carry event + v (schema
# version 1) — exercised end-to-end through the real emitters.
if ! JAX_PLATFORMS=cpu BA_TPU_COMPILE_CACHE=0 \
        python scripts/check_metrics_schema.py; then
    echo "metrics JSONL schema check failed" >&2
    exit 1
fi

echo "== tier-1 suite =="
# Compilation-cache hygiene (ROADMAP decision, ISSUE 2): tier-1 SHARES
# the persistent XLA cache, enabled explicitly by tests/conftest.py —
# previously it was enabled as a SIDE EFFECT of whichever test built a
# JaxBackend first, so cache state depended on test order.  Cold is not
# an option for this suite: measured on the 2-vCPU CI host,
# tests/test_crypto.py ALONE takes 8m19s cold while the entire warm
# suite fits ~10m against the fixed 870 s timeout below.  Compile
# regressions are hunted with the documented opt-out
# (BA_TPU_COMPILE_CACHE=0 env) on targeted files; the knob itself is
# covered by tests/test_platform.py.
# Verbatim from ROADMAP.md ("Tier-1 verify"); keep the two in sync.
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
