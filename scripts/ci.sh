#!/usr/bin/env bash
# CI entry: hot-path lint + the tier-1 suite (ROADMAP.md, verbatim).
#
# The lint guards the pipelined sweep engine's contract (ISSUE 1): the
# round-loop modules under ba_tpu/parallel/ must never re-grow
#
#   - block_until_ready      — on the tunnel backend it acks the dispatch
#                              without awaiting execution (README
#                              methodology note), and in a round loop ANY
#                              host sync serializes host and device; the
#                              engine's only sync is the depth-delayed
#                              device_get retire;
#   - host np. conversions   — np.asarray/np.array on device values drain
#                              the queue through the host (multihost.py's
#                              documented put_global ingestion is the one
#                              sanctioned np user in the package);
#   - host per-round key splits in pipeline.py — keys are derived ON
#                              DEVICE from the folded counter
#                              (KeySchedule); a jr.split reappearing
#                              there means the host is back in the
#                              per-round loop.
#
# Greps are over source text (comments included) by design: cheap, zero
# deps, and the banned idioms have no legitimate spelling in these files.

set -u
cd "$(dirname "$0")/.."

fail=0

echo "== hot-path lint: ba_tpu/parallel =="
if grep -rn "block_until_ready" ba_tpu/parallel/ --include='*.py'; then
    echo "LINT FAIL: block_until_ready inside ba_tpu/parallel/" >&2
    fail=1
fi
# \b keeps jnp.asarray (device-side) out of the match; scope is the
# round-loop modules (mesh/multihost build host-side topology and are
# the package's sanctioned numpy users).
if grep -rn "\bnp\.asarray(\|\bnp\.array(\|\bnumpy\.asarray(" \
        ba_tpu/parallel/pipeline.py ba_tpu/parallel/sweep.py; then
    echo "LINT FAIL: host numpy conversion in a parallel round-loop module" >&2
    fail=1
fi
if grep -n "jr\.split\|random\.split" ba_tpu/parallel/pipeline.py; then
    echo "LINT FAIL: host key split in pipeline.py (keys must derive" \
         "on device from the KeySchedule counter)" >&2
    fail=1
fi
if [ "$fail" -ne 0 ]; then
    echo "hot-path lint failed" >&2
    exit 1
fi
echo "hot-path lint OK"

echo "== obs host-only lint: ba_tpu/core ba_tpu/ops =="
# The observability layer (ISSUE 2) is HOST-only by contract: a span or
# metrics.emit inside a jitted/scan body would time tracing instead of
# execution (or force a host callback sync).  The jitted math lives in
# ba_tpu/core and ba_tpu/ops, so — mirroring the hot-path lint above —
# those trees must never reference the sink or the tracer; wiring
# belongs in runtime/, parallel/ loop drivers, crypto host paths, and
# bench.py.
if grep -rn "metrics\.emit\|ba_tpu\.obs\|ba_tpu import obs\|obs\.span" \
        ba_tpu/core/ ba_tpu/ops/ --include='*.py'; then
    echo "LINT FAIL: host-only instrumentation referenced inside a" \
         "jitted module tree (ba_tpu/core or ba_tpu/ops)" >&2
    exit 1
fi
echo "obs host-only lint OK"

echo "== metrics JSONL schema check =="
# Every record the layer emits must parse and carry event + v (schema
# version 1) — exercised end-to-end through the real emitters.
if ! JAX_PLATFORMS=cpu BA_TPU_COMPILE_CACHE=0 \
        python scripts/check_metrics_schema.py; then
    echo "metrics JSONL schema check failed" >&2
    exit 1
fi

echo "== tier-1 suite =="
# Compilation-cache hygiene (ROADMAP decision, ISSUE 2): tier-1 SHARES
# the persistent XLA cache, enabled explicitly by tests/conftest.py —
# previously it was enabled as a SIDE EFFECT of whichever test built a
# JaxBackend first, so cache state depended on test order.  Cold is not
# an option for this suite: measured on the 2-vCPU CI host,
# tests/test_crypto.py ALONE takes 8m19s cold while the entire warm
# suite fits ~10m against the fixed 870 s timeout below.  Compile
# regressions are hunted with the documented opt-out
# (BA_TPU_COMPILE_CACHE=0 env) on targeted files; the knob itself is
# covered by tests/test_platform.py.
# Verbatim from ROADMAP.md ("Tier-1 verify"); keep the two in sync.
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
