"""Same-window A/B over fused-kernel rounds-per-dispatch K (one process,
interleaved reps so service drift cancels).  Run ALONE.

K>1 chains K independent agreement rounds inside one kernel dispatch
(ops/sweep_step.py), dividing per-dispatch overhead by K; this script
measures where that amortization saturates.  Throughput is reported in
agreement ROUNDS/s (batch * K per dispatch), so K values compare directly.
ROUNDS_AB_K picks the candidates; ROUNDS_AB_TILE pins the kernel tile."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ab_common import emit, interleaved_ab, sweep_fixture
    from ba_tpu.ops.sweep_step import fused_signed_sweep_step

    rounds = [int(k) for k in
              os.environ.get("ROUNDS_AB_K", "1,4,8,15").split(",")]
    tile = int(os.environ.get("ROUNDS_AB_TILE", 0)) or None
    batch, m = 10240, 3
    iters, reps = 30, 3
    states, oks = sweep_fixture(batch)

    def make_step(k_rounds):
        @jax.jit
        def step(seed):
            acc = jnp.int32(0)
            for i, (st, okb) in enumerate(zip(states, oks)):
                dec = fused_signed_sweep_step(
                    seed + i, st.order, st.leader, st.faulty, st.alive,
                    okb, m, k_rounds, tile=tile,
                )
                acc += dec.astype(jnp.int32).sum()
            return acc
        return step

    best = interleaved_ab({k: make_step(k) for k in rounds}, iters, reps)
    emit(
        "fused-rounds-ab", batch, iters,
        {
            str(k): (
                {"error": "compile-failed (see stderr)"}
                if e == float("inf")
                else {"elapsed_s": round(e, 4),
                      "rounds_per_sec": round(batch * k * iters / e, 1)}
            )
            for k, e in best.items()
        },
        tile=tile or "default",
    )


if __name__ == "__main__":
    main()
