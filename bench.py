"""Benchmark: agreement-rounds/sec on the reference's own headline case.

Workload: BASELINE.json config #1 — OM(1), n=4 generals, 1 traitor
lieutenant — batched over 131072 independent consensus instances on one
chip.  The reference's ceiling for the same case is ~10 rounds/sec: its
``wait_majority`` polls at 0.1 s (ba.py:287-289) and the run-loop tick adds
another 0.1 s (ba.py:301), so one agreement can never finish faster than a
tick; ``vs_baseline`` is measured against that 10 rounds/sec floor.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

from __future__ import annotations

import json
import os
import time


REFERENCE_ROUNDS_PER_SEC = 10.0  # 0.1 s poll floor, ba.py:287-301


def main() -> None:
    platform = os.environ.get("BA_TPU_BENCH_PLATFORM")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp
    import jax.random as jr

    from ba_tpu.core import make_state, om1_agreement
    from ba_tpu.core.types import ATTACK

    batch = int(os.environ.get("BA_TPU_BENCH_BATCH", 131072))
    n = 4
    faulty = jnp.zeros((batch, n), bool).at[:, 2].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)

    @jax.jit
    def round_fn(key, state):
        out = om1_agreement(key, state)
        # Reduce to a tiny result so timing measures the round, not D2H.
        return (
            out["decision"].astype(jnp.int32).sum(),
            out["needed"].sum(),
        )

    key = jr.key(0)
    # Warmup / compile.
    jax.block_until_ready(round_fn(key, state))

    iters = 30
    t0 = time.perf_counter()
    for i in range(iters):
        res = round_fn(jr.fold_in(key, i), state)
    jax.block_until_ready(res)
    elapsed = time.perf_counter() - t0

    rounds_per_sec = batch * iters / elapsed
    print(
        json.dumps(
            {
                "metric": "agreement-rounds/sec",
                "value": round(rounds_per_sec, 1),
                "unit": "rounds/s (OM(1), n=4, 1 traitor, B=%d)" % batch,
                "vs_baseline": round(rounds_per_sec / REFERENCE_ROUNDS_PER_SEC, 1),
                "platform": jax.devices()[0].platform,
                "batch": batch,
                "iters": iters,
                "elapsed_s": round(elapsed, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
