"""Benchmark: all five BASELINE.json configs on one chip, one JSON line.

Configs (BASELINE.md:31-36):

1. ``om1_n4``        — OM(1), n=4, 1 traitor, unsigned; the reference's own
                       headline case.  Its ceiling is ~10 rounds/s: its
                       ``wait_majority`` polls at 0.1 s (ba.py:287-289) and
                       the run-loop tick adds another 0.1 s (ba.py:301), so
                       one agreement can never beat a tick.
2. ``om3_n10``       — OM(3), n=10, 3 traitors, unsigned, dense EIG tree.
3. ``sm1_n64_signed``— SM(1), n=64, signed: the batched Ed25519 device
                       verify (the tracked "verifies/sec" metric) plus the
                       full signed round.
4. ``n1024_m32``     — n=1024 generals, m=32, single instance, collapsed
                       SM relay (the EIG tree would need n^32 cells).
5. ``sweep10k_signed``— the north star: 10k independent (n<=1024, m=3)
                       signed instances per round.  Host signing uses the
                       per-(instance, value) tables (2 signs/commander,
                       one-time setup); each timed round runs the whole
                       device pipeline — round-1 broadcast, signature-mask
                       gather, 3 collapsed relay rounds, quorum.

The primary metric stays config #1's rounds/s (continuity with round 1's
BENCH json); every config's numbers ride in the same line under "configs",
with rough analytic bytes-per-round estimates so "fast" is falsifiable:
these workloads are int8/bool elementwise + RNG (VPU work, no matmuls), so
the honest accounting is achieved bytes/s vs HBM peak — except Ed25519,
which is int32-multiply bound.

``--profile DIR`` wraps the timed loops in ``jax.profiler.trace`` (view
with TensorBoard or xprof).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time


REFERENCE_ROUNDS_PER_SEC = 10.0  # 0.1 s poll floor, ba.py:287-301
HBM_PEAK_GBPS = float(os.environ.get("BA_TPU_HBM_PEAK_GBPS", 1200.0))  # v4 chip


def _timed(fn, make_args, iters, reps=3):
    """Compile/warm on iteration 0, then time ``iters`` dispatches.

    Takes the fastest of ``reps`` repetitions: the TPU-tunnel backend is a
    shared service with +-2x run-to-run noise (measured r2), and min-of-reps
    is the standard noise-robust estimate of achievable throughput.

    The sync at each boundary is ``jax.device_get`` (a host fetch), NOT
    ``block_until_ready``: on the tunnel backend block_until_ready returns
    after the dispatch is acknowledged, not executed (measured r2: 0.1 ms
    "timings" for a 190 ms program), while a host fetch genuinely drains
    the queue.  The benched step functions all return scalars, so the
    fetch itself costs one small round-trip.
    """
    import jax

    jax.device_get(fn(*make_args(0)))
    best = float("inf")
    for r in range(reps):
        t0 = time.perf_counter()
        res = None
        for i in range(1, iters + 1):
            res = fn(*make_args(r * iters + i))
        jax.device_get(res)
        best = min(best, time.perf_counter() - t0)
    return best


def bench_om1_n4(jax, jnp, jr):
    from ba_tpu.core import make_state, om1_agreement
    from ba_tpu.core.types import ATTACK

    batch = int(os.environ.get("BA_TPU_BENCH_BATCH", 4194304))
    n = 4
    faulty = jnp.zeros((batch, n), bool).at[:, 2].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)

    @jax.jit
    def step(key, state):
        out = om1_agreement(key, state)
        return out["decision"].astype(jnp.int32).sum(), out["needed"].sum()

    key = jr.key(0)
    iters = 30
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i), state), iters)
    bytes_round = batch * (2 * n * n + 5 * n)  # answer+coin cubes, int8 rows
    return {
        "rounds_per_sec": round(batch * iters / elapsed, 1),
        "batch": batch, "n": n, "m": 1, "iters": iters,
        "elapsed_s": round(elapsed, 4),
        "bytes_per_round_est": bytes_round,
        "achieved_gbps_est": round(bytes_round * iters / elapsed / 1e9, 2),
        "bound": "dispatch/latency (tiny per-round footprint)",
    }


def bench_om3_n10(jax, jnp, jr):
    from ba_tpu.core import eig_agreement, make_state
    from ba_tpu.core.types import ATTACK

    batch = int(os.environ.get("BA_TPU_BENCH_EIG_BATCH", 131072))
    n, m = 10, 3
    faulty = jnp.zeros((batch, n), bool).at[:, [2, 5, 7]].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)

    @jax.jit
    def step(key, state):
        out = eig_agreement(key, state, m)
        return out["decision"].astype(jnp.int32).sum(), out["needed"].sum()

    key = jr.key(1)
    iters = 20
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i), state), iters)
    # EIG levels 1..m: n^l cells per general, touched ~3x (coins, send
    # tensor, resolve pass), all int8.
    cells = sum(n ** l for l in range(1, m + 1))
    bytes_round = batch * n * cells * 3
    return {
        "rounds_per_sec": round(batch * iters / elapsed, 1),
        "batch": batch, "n": n, "m": m, "iters": iters,
        "elapsed_s": round(elapsed, 4),
        "bytes_per_round_est": bytes_round,
        "achieved_gbps_est": round(bytes_round * iters / elapsed / 1e9, 2),
        "bound": "HBM bandwidth (dense EIG tree materialisation)",
    }


def bench_sm1_n64_signed(jax, jnp, jr):
    import numpy as np

    from ba_tpu.core import make_state, sm_agreement
    from ba_tpu.core.types import ATTACK
    from ba_tpu.crypto.ed25519 import verify
    from ba_tpu.crypto.signed import commander_keys, sign_received

    batch = int(os.environ.get("BA_TPU_BENCH_SIG_BATCH", 64))
    n, m = 64, 1
    faulty = jnp.zeros((batch, n), bool).at[:, 1].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)

    # (a) the raw batched-verify kernel at the chunk-optimal lane count
    # (ba_tpu.crypto.signed._verify_chunk): per-dispatch tunnel latency is
    # tens of ms, so small batches measure latency, not the kernel.  The
    # valid signed broadcast tiles up to the verify batch.  Inputs VARY per
    # timed call: the tunnel backend memoizes repeat dispatches of byte-
    # identical buffers, which fakes absurd throughput (measured r2: 20k
    # verifies "in 2.6 ms").  Distinct signed broadcasts per dispatch, all
    # valid, cycled across iterations.
    sks, pks = commander_keys(batch)

    from ba_tpu.crypto.signed import _verify_chunk

    # Default to the production chunk size (64k pallas / 4k jnp — the jnp
    # ladder collapses past ~4k lanes); BA_TPU_BENCH_VERIFY_BATCH overrides.
    nv = int(os.environ.get("BA_TPU_BENCH_VERIFY_BATCH", 0)) or _verify_chunk()
    tile = -(-nv // (batch * n))
    pk_flat = jnp.asarray(
        np.tile(np.repeat(pks, n, axis=0), (tile, 1))[:nv]
    )
    rng = np.random.default_rng(2)
    v_iters, v_reps = 3, 3
    variants = []
    for v in range(1 + v_reps * v_iters):  # one per dispatch: warmup + reps*iters
        received = rng.integers(0, 2, (batch, n))  # distinct, all validly signed
        msgs, sigs = sign_received(sks, pks, received)
        variants.append(
            (pk_flat,
             jnp.asarray(np.tile(msgs.reshape(batch * n, -1), (tile, 1))[:nv]),
             jnp.asarray(np.tile(sigs.reshape(batch * n, 64), (tile, 1))[:nv]))
        )
    vjit = jax.jit(verify)
    first = jax.device_get(vjit(*variants[0]))
    assert bool(jnp.all(first)), "bench signatures must all verify"
    v_elapsed = _timed(
        lambda *a: vjit(*a), lambda i: variants[i % len(variants)],
        v_iters, reps=v_reps,
    )
    verifies_per_sec = nv * v_iters / v_elapsed

    # (b) the full signed agreement round on-device (verify mask reused —
    # commander signatures are per-(instance, value), already checked).
    sig_valid = jnp.ones((batch, n), bool)

    @jax.jit
    def step(key, state, sig_valid):
        out = sm_agreement(key, state, m, None, sig_valid, None, False)
        return out["decision"].astype(jnp.int32).sum()

    key = jr.key(3)
    iters = 20
    elapsed = _timed(
        step, lambda i: (jr.fold_in(key, i), state, sig_valid), iters
    )
    # ~1.7M int32 multiplies per verify: ~5.7k field muls — 256-step
    # double-and-add-always [h]A ladder (4.6k), 63-add fixed-base [S]B
    # tree (0.6k), 2 decompressions (0.5k) — x ~300 multiplies each
    # (22x22 limb products + carry/fold passes).
    est_mults = 1.7e6
    return {
        "rounds_per_sec": round(batch * iters / elapsed, 1),
        "ed25519_verifies_per_sec": round(verifies_per_sec, 1),
        "verify_batch": nv, "batch": batch, "n": n, "m": m,
        "iters": iters, "elapsed_s": round(elapsed, 4),
        "verify_elapsed_s": round(v_elapsed, 4),
        "est_int32_gmults_per_sec": round(verifies_per_sec * est_mults / 1e9, 1),
        "bound": "compute (int32 limb multiplies on VPU)",
    }


def bench_n1024_m32(jax, jnp, jr):
    from ba_tpu.core import make_state, sm_agreement
    from ba_tpu.core.types import ATTACK

    n, m = 1024, 32
    faulty = jnp.zeros((1, n), bool).at[:, :m].set(True)
    state = make_state(1, n, order=ATTACK, faulty=faulty)
    inner = 100  # sequential rounds per dispatch: keeps the TPU-tunnel
    # dispatch latency (tens of ms, high variance) out of the measurement

    @jax.jit
    def step(key, state):
        def one(acc, k):
            out = sm_agreement(k, state, m, None, None, None, True)
            return acc + out["decision"].astype(jnp.int32).sum(), None

        acc, _ = jax.lax.scan(one, jnp.int32(0), jr.split(key, inner))
        return acc

    key = jr.key(4)
    iters = 5
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i), state), iters)
    bytes_round = m * n * 2 * 3  # per relay round: packed-u8 draws + seen bools
    return {
        "rounds_per_sec": round(inner * iters / elapsed, 1),
        "batch": 1, "n": n, "m": m, "iters": inner * iters,
        "elapsed_s": round(elapsed, 4),
        "bytes_per_round_est": bytes_round,
        "bound": "sequential-depth latency (single instance, 32 dependent "
                 "relay rounds/agreement)",
    }


def bench_sweep10k_signed(jax, jnp, jr):
    import numpy as np

    from ba_tpu.core import sm_agreement
    from ba_tpu.crypto.signed import (
        commander_keys,
        sign_value_tables,
        verify_received,
    )
    from ba_tpu.parallel import make_sweep_state

    batch = int(os.environ.get("BA_TPU_BENCH_SWEEP_BATCH", 10240))
    cap, m = 1024, 3
    state = make_sweep_state(jr.key(5), batch, cap)

    # One-time setup, off the clock: per-instance keys, 2 signs each, and
    # one device verify of each distinct signature ([B, 2] tables).
    t0 = time.perf_counter()
    sks, pks = commander_keys(batch)
    msgs_t, sigs_t = sign_value_tables(sks, pks)
    setup_sign_s = time.perf_counter() - t0
    # Warm the verify kernel on a same-shape but different-content call:
    # shape-identical so the one-time XLA/Mosaic compile is not billed as
    # throughput, content-distinct because the tunnel backend memoizes
    # repeat dispatches of byte-identical buffers (see bench_sm1 note).
    warm_sigs = sigs_t.copy()
    warm_sigs[..., 0] ^= 0xFF
    jax.device_get(verify_received(pks, msgs_t, warm_sigs))
    t0 = time.perf_counter()
    ok = verify_received(pks, msgs_t, sigs_t)  # [B, 2]
    jax.device_get(ok)  # host fetch: truly drain (see _timed)
    setup_verify_s = time.perf_counter() - t0
    table_verifies_per_sec = 2 * batch / setup_verify_s

    # The timed step is the whole per-round signed pipeline on device:
    # round-1 equivocation broadcast -> per-copy signature-mask gather from
    # the verified tables -> m collapsed relay rounds -> quorum.
    from ba_tpu.core.om import round1_broadcast
    from ba_tpu.crypto.signed import sig_valid_from_tables

    @jax.jit
    def step(key, state, ok):
        k1, k2 = jr.split(key)
        received = round1_broadcast(k1, state)
        sig_valid = sig_valid_from_tables(ok, received)
        out = sm_agreement(k2, state, m, None, sig_valid, received, True)
        return out["decision"].astype(jnp.int32).sum()

    key = jr.key(6)
    iters = 50
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i), state, ok), iters)
    # Per round: m packed-u8 draw cubes [B, cap, 2] + seen/broadcast rows.
    bytes_round = batch * cap * (m * 2 + 8)
    rps = batch * iters / elapsed
    # The honest north-star accounting (VERDICT r2 missing #1): a fresh
    # key-set pays setup (host signing + the one device table-verify)
    # before any round runs, so report rounds/s *including* setup at
    # stated amortization horizons, plus the horizon where the
    # including-setup rate crosses the 1M target.
    setup_total = setup_sign_s + setup_verify_s
    t_iter = elapsed / iters
    incl = {
        f"h{h}": round(batch * h / (setup_total + h * t_iter), 1)
        for h in (50, 100, 500, 5000)
    }
    if batch / 1e6 > t_iter:
        crossover = setup_total / (batch / 1e6 - t_iter)
        crossover_iters = int(crossover) + 1
    else:
        crossover_iters = None  # never crosses at this throughput
    return {
        "rounds_per_sec": round(rps, 1),
        "vs_target_1M": round(rps / 1e6, 3),
        "batch": batch, "n_max": cap, "m": m, "iters": iters,
        "elapsed_s": round(elapsed, 4),
        "setup_sign_s": round(setup_sign_s, 2),
        "setup_verify_s": round(setup_verify_s, 2),
        "table_verifies_per_sec": round(table_verifies_per_sec, 1),
        "rounds_per_sec_incl_setup": incl,
        "incl_setup_crossover_1M_iters": crossover_iters,
        "bytes_per_round_est": bytes_round,
        "achieved_gbps_est": round(bytes_round * iters / elapsed / 1e9, 2),
        "bound": "VPU throughput (packed-u8 RNG + elementwise relay; "
                 "far from HBM peak)",
        "note": "signing+table-verify are one-time setup per key-set; "
                "rounds_per_sec_incl_setup charges them at each horizon H "
                "(batch*H / (setup + H*t_iter))",
    }


CONFIGS = {
    # Latency-sensitive configs first: dispatch through the TPU tunnel gets
    # noticeably slower once the big Ed25519-verify programs have run
    # (measured r2: config #4 drops ~100x when sequenced after #3).
    "om1_n4": bench_om1_n4,
    "om3_n10": bench_om3_n10,
    "n1024_m32": bench_n1024_m32,
    "sweep10k_signed": bench_sweep10k_signed,
    "sm1_n64_signed": bench_sm1_n64_signed,
}


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--profile", metavar="DIR", default=None,
                        help="write a jax.profiler trace to DIR (works on "
                             "local backends, e.g. BA_TPU_BENCH_PLATFORM=cpu "
                             "or directly-attached TPU; the shared TPU-tunnel "
                             "backend does not serve the profiler and hangs)")
    parser.add_argument("--configs", default=os.environ.get(
        "BA_TPU_BENCH_CONFIGS", ",".join(CONFIGS)),
        help="comma-separated subset of: " + ",".join(CONFIGS))
    args = parser.parse_args()

    platform = os.environ.get("BA_TPU_BENCH_PLATFORM")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp
    import jax.random as jr

    trace = (jax.profiler.trace(args.profile) if args.profile
             else contextlib.nullcontext())
    names = [n.strip() for n in args.configs.split(",") if n.strip()]
    unknown = [n for n in names if n not in CONFIGS]
    if not names or unknown:
        parser.error(
            f"unknown config(s) {unknown or args.configs!r}; "
            f"valid: {', '.join(CONFIGS)}"
        )
    results = {}
    with trace:
        for name in names:
            print(f"bench: {name} ...", file=sys.stderr, flush=True)
            results[name] = CONFIGS[name](jax, jnp, jr)

    primary_name = "om1_n4" if "om1_n4" in results else next(iter(results))
    primary = results[primary_name]
    unit = "rounds/s (%s)" % (
        "OM(1), n=4, 1 traitor, B=%d" % primary.get("batch", 0)
        if primary_name == "om1_n4"
        else primary_name
    )
    line = {
        "metric": "agreement-rounds/sec",
        "value": primary["rounds_per_sec"],
        "unit": unit,
        "vs_baseline": round(
            primary["rounds_per_sec"] / REFERENCE_ROUNDS_PER_SEC, 1
        ),
        "platform": jax.devices()[0].platform,
        "hbm_peak_gbps_assumed": HBM_PEAK_GBPS,
        "variance_note": "shared TPU service: ~2x run-to-run noise; "
                         "min-of-3 per config applied.  All timings are "
                         "host-fetch-synced (jax.device_get): r2 found "
                         "block_until_ready on this backend acks the "
                         "dispatch without awaiting execution, so earlier "
                         "rounds' numbers for dispatch-bound configs were "
                         "enqueue rates, not throughput",
        "configs": results,
    }
    if "sweep10k_signed" in results:
        line["north_star_rounds_per_sec"] = results["sweep10k_signed"][
            "rounds_per_sec"
        ]
    if "sm1_n64_signed" in results:
        line["ed25519_verifies_per_sec"] = results["sm1_n64_signed"][
            "ed25519_verifies_per_sec"
        ]
    print(json.dumps(line))


if __name__ == "__main__":
    main()
