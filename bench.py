"""Benchmark: all five BASELINE.json configs on one chip, one JSON line.

Configs (BASELINE.md:31-36):

1. ``om1_n4``        — OM(1), n=4, 1 traitor, unsigned; the reference's own
                       headline case.  Its ceiling is ~10 rounds/s: its
                       ``wait_majority`` polls at 0.1 s (ba.py:287-289) and
                       the run-loop tick adds another 0.1 s (ba.py:301), so
                       one agreement can never beat a tick.
2. ``om3_n10``       — OM(3), n=10, 3 traitors, unsigned EIG (deepest
                       level fused: MXU einsum + Binomial popcount).
3. ``sm1_n64_signed``— SM(1), n=64, signed: the batched Ed25519 device
                       verify (the tracked "verifies/sec" metric) plus the
                       full signed round.
4. ``n1024_m32``     — n=1024 generals, m=32, single instance, collapsed
                       SM relay (the EIG tree would need n^32 cells).
5. ``sweep10k_signed``— the north star: 10k independent (n<=1024, m=3)
                       signed instances per round.  Host signing uses the
                       per-(instance, value) tables (2 signs/commander,
                       one-time setup); each timed round runs the whole
                       device pipeline — round-1 broadcast, signature-mask
                       gather, 3 collapsed relay rounds, quorum.  Reports
                       including-setup rates at stated horizons.

Framework extensions beyond the 5 BASELINE configs:

6. ``eig_n1024``     — the EIG tree at n=1024 (m=2; r4: deepest level
                       fused, the GiB-scale dense tensors never build).
7. ``interactive_b1``— per-round B=1 latency (median/p10/p90), the
                       interactive REPL case the reference serves in
                       ~0.2-0.3 s.
8. ``failover_sweep``— R rounds of kill -> detect -> re-elect -> agree
                       per dispatch, A/B'd against the same scan without
                       the election stage.
9. ``pipeline_sweep``— the pipelined multi-round engine
                       (parallel/pipeline.py: on-device key schedule,
                       donated buffers, lax.scan megasteps, depth-k
                       in-flight dispatches) A/B'd same-window against
                       the blocking per-round driver at EQUAL round
                       count.
10. ``scenario_sweep``— the pipelined MUTATING campaign (ba_tpu.scenario
                       compiled into the donated megastep: kills,
                       re-election, strategies, IC1/IC2 verdicts) A/B'd
                       same-window against the sequential failover
                       driver at EQUAL rounds and kill schedule.
11. ``scenario_long`` — (opt-in: --configs scenario_long) the STREAMING
                       campaign: >=100k rounds sparse-lowered at
                       O(chunk) host memory, double-buffered plane
                       staging, A/B'd against the equivalent
                       dense-lowered short campaign; the artifact for
                       BENCH_longrun_r9.json.
12. ``resilience``    — (opt-in: --configs resilience) the execution
                       supervisor's cost: uninterrupted baseline vs
                       supervised+checkpointed vs supervised with an
                       injected fatal fault (checkpoint recovery) vs a
                       real mid-campaign SIGKILL + cross-process
                       auto-resume, all bit-identical; the artifact
                       for BENCH_resilience_r10.json.

``--stages`` replaces the config suite with a per-kernel breakdown of the
verify pipeline plus two synthetic probes (raw VPU int32 multiply, and
the chained-p_mul FLOOR — compound kernels beat it ~2x, which is why the
verify roofline instead divides by the same-window window-ladder leg
inside bench_sm1_n64_signed).

The primary metric stays config #1's rounds/s (continuity with round 1's
BENCH json); every config's numbers ride in the detail artifact under
"configs", with rough analytic bytes-per-round estimates so "fast" is
falsifiable: the agreement workloads are int8/bool elementwise + RNG
(VPU) plus, since r4, the fused EIG level's int8 einsum (MXU); bandwidth
bounds are judged against the measured stream probe
(bench_hbm_copy_peak), Ed25519 against the field-multiply probe.

``--profile DIR`` wraps the timed loops in ``jax.profiler.trace`` (view
with TensorBoard or xprof).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time


REFERENCE_ROUNDS_PER_SEC = 10.0  # 0.1 s poll floor, ba.py:287-301
HBM_PEAK_GBPS = float(os.environ.get("BA_TPU_HBM_PEAK_GBPS", 1200.0))  # v4 chip


def make_key(seed: int):
    """Bench PRNG keys honor the BA_TPU_RNG impl knob (core.rng.make_key):
    rbg = TPU hardware RngBitGenerator for coin draws, threefry derivation.
    Lazy import so bench's platform selection still happens before jax init.
    """
    from ba_tpu.core.rng import make_key as _mk

    return _mk(seed)


def _timed(fn, make_args, iters, reps=3):
    """Compile/warm on iteration 0, then time ``iters`` dispatches.

    Takes the fastest of ``reps`` repetitions: the TPU-tunnel backend is a
    shared service with +-2x run-to-run noise (measured r2), and min-of-reps
    is the standard noise-robust estimate of achievable throughput.

    The sync at each boundary is ``jax.device_get`` (a host fetch), NOT
    ``block_until_ready``: on the tunnel backend block_until_ready returns
    after the dispatch is acknowledged, not executed (measured r2: 0.1 ms
    "timings" for a 190 ms program), while a host fetch genuinely drains
    the queue.  The benched step functions all return scalars, so the
    fetch itself costs one small round-trip.
    """
    import jax

    jax.device_get(fn(*make_args(0)))
    best = float("inf")
    for r in range(reps):
        t0 = time.perf_counter()
        res = None
        for i in range(1, iters + 1):
            res = fn(*make_args(r * iters + i))
        jax.device_get(res)
        best = min(best, time.perf_counter() - t0)
    return best


def bench_om1_n4(jax, jnp, jr):
    from ba_tpu.core import make_state, om1_agreement
    from ba_tpu.core.types import ATTACK

    batch = int(os.environ.get("BA_TPU_BENCH_BATCH", 4194304))
    n = 4
    faulty = jnp.zeros((batch, n), bool).at[:, 2].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)

    # state is constant across rounds: close over it (seed-only dispatch,
    # same rationale and measurement as the sweep config below).
    @jax.jit
    def step(key):
        out = om1_agreement(key, state)
        return out["decision"].astype(jnp.int32).sum(), out["needed"].sum()

    key = make_key(0)
    iters = 30
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i),), iters)
    bytes_round = batch * (2 * n * n + 5 * n)  # answer+coin cubes, int8 rows
    return {
        "rounds_per_sec": round(batch * iters / elapsed, 1),
        "batch": batch, "n": n, "m": 1, "iters": iters,
        "elapsed_s": round(elapsed, 4),
        "bytes_per_round_est": bytes_round,
        "achieved_gbps_est": round(bytes_round * iters / elapsed / 1e9, 2),
        "bound": "VPU elementwise + per-iter dispatch (seed-only dispatch "
                 "r3: shipping the state pytree per call was 14x slower)",
    }


def bench_om3_n10(jax, jnp, jr):
    from ba_tpu.core import eig_agreement, make_state
    from ba_tpu.core.types import ATTACK

    batch = int(os.environ.get("BA_TPU_BENCH_EIG_BATCH", 131072))
    n, m = 10, 3
    faulty = jnp.zeros((batch, n), bool).at[:, [2, 5, 7]].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)
    max_liars = int(faulty.sum(-1).max())  # derived, never hardcoded

    @jax.jit
    def step(key):  # state closed over: constant across rounds
        out = eig_agreement(key, state, m, max_liars)
        return out["decision"].astype(jnp.int32).sum(), out["needed"].sum()

    key = make_key(1)
    iters = 20
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i),), iters)
    # Fused deepest level (r4): levels 1..m-1 materialize (touched ~3x);
    # the n^m level is an einsum + popcount words over n^(m-1) paths.
    cells = sum(n ** l for l in range(1, m))
    bytes_round = batch * n * (cells * 3 + n ** (m - 1) * (4 + 4))
    return {
        "rounds_per_sec": round(batch * iters / elapsed, 1),
        "batch": batch, "n": n, "m": m, "iters": iters,
        "elapsed_s": round(elapsed, 4),
        "bytes_per_round_est": bytes_round,
        "achieved_gbps_est": round(bytes_round * iters / elapsed / 1e9, 2),
        "bound": "VPU elementwise + MXU einsum (fused deepest EIG level; "
                 "dense-tree form: BA_TPU_EIG_FUSED=0)",
    }


def bench_sm1_n64_signed(jax, jnp, jr):
    import numpy as np

    from ba_tpu.core import make_state, sm_agreement
    from ba_tpu.core.types import ATTACK
    from ba_tpu.crypto.ed25519 import verify
    from ba_tpu.crypto.signed import commander_keys, sign_received

    batch = int(os.environ.get("BA_TPU_BENCH_SIG_BATCH", 64))
    n, m = 64, 1
    faulty = jnp.zeros((batch, n), bool).at[:, 1].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)

    # (a) the raw batched-verify kernel at the chunk-optimal lane count
    # (ba_tpu.crypto.signed._verify_chunk): per-dispatch tunnel latency is
    # tens of ms, so small batches measure latency, not the kernel.  The
    # valid signed broadcast tiles up to the verify batch.  Inputs VARY per
    # timed call: the tunnel backend memoizes repeat dispatches of byte-
    # identical buffers, which fakes absurd throughput (measured r2: 20k
    # verifies "in 2.6 ms").  Distinct signed broadcasts per dispatch, all
    # valid, cycled across iterations.
    sks, pks = commander_keys(batch)

    from ba_tpu.crypto.signed import _verify_chunk

    # Default to the production chunk size (64k pallas / 4k jnp — the jnp
    # ladder collapses past ~4k lanes); BA_TPU_BENCH_VERIFY_BATCH overrides.
    nv = int(os.environ.get("BA_TPU_BENCH_VERIFY_BATCH", 0)) or _verify_chunk()
    tile = -(-nv // (batch * n))
    pk_flat = jnp.asarray(
        np.tile(np.repeat(pks, n, axis=0), (tile, 1))[:nv]
    )
    rng = np.random.default_rng(2)
    v_iters, v_reps = 3, 3
    variants = []
    for v in range(1 + v_reps * v_iters):  # one per dispatch: warmup + reps*iters
        received = rng.integers(0, 2, (batch, n))  # distinct, all validly signed
        msgs, sigs = sign_received(sks, pks, received)
        variants.append(
            (pk_flat,
             jnp.asarray(np.tile(msgs.reshape(batch * n, -1), (tile, 1))[:nv]),
             jnp.asarray(np.tile(sigs.reshape(batch * n, 64), (tile, 1))[:nv]))
        )
    vjit = jax.jit(verify)
    first = jax.device_get(vjit(*variants[0]))
    assert bool(jnp.all(first)), "bench signatures must all verify"
    # Same-window roofline: verify reps INTERLEAVED with field-mul probe
    # reps (see make_fieldmul_probe) so numerator and denominator share
    # one service window — the r3 pct_of_peak doubled with the weather
    # because the two sides were measured in different windows.
    fm_fn, fm_variants, fm_per_dispatch = make_fieldmul_probe(jax, jnp, jr)
    jax.device_get(fm_fn(*fm_variants[0]))  # compile/warm off the clock
    # Third interleaved leg: the random-linear-combination BATCH verifier
    # (ed25519.verify_rlc) on the same signed content — one combined
    # equation for all nv lanes, A laddered once per commander key
    # (pk_group=n).  Same window as the per-signature kernel, so the
    # speedup ratio is weather-free.
    from ba_tpu.crypto.ed25519 import verify_rlc
    from ba_tpu.crypto.signed import fresh_rlc_coeffs

    rlc_fn = jax.jit(
        lambda p, ms, s, z: verify_rlc(p, ms, s, z, pk_group=n)[0],
        static_argnames=(),
    )
    z_variants = [
        jnp.asarray(fresh_rlc_coeffs(nv)) for _ in range(len(variants))
    ]
    first_rlc = jax.device_get(rlc_fn(*variants[0], z_variants[0]))
    assert bool(first_rlc), "bench RLC batch must verify"
    # Fourth interleaved leg: the 256-bit window-ladder kernel ALONE on
    # the same lanes — the roofline denominator in the verify's own unit
    # AND its own code: the pipeline's dominant stage cannot run faster
    # inside the pipeline than standalone, so pct <= ~100 by
    # construction.  (The chained-p_mul probe stays as a floor: compound
    # kernels beat it ~2x via cross-mul ILP, which is exactly why a
    # synthetic chain is not a valid peak — r3's lesson, re-learned.)
    from ba_tpu.crypto import field as _F
    from ba_tpu.crypto.ed25519 import decompress as _dec, _use_pallas

    if _use_pallas():
        from ba_tpu.ops.ladder import window_mult as _lmult
        from ba_tpu.ops.modl import reduce_mod_l_planes as _lmodl
    else:
        from ba_tpu.crypto.ed25519 import scalar_mult as _lmult
        from ba_tpu.crypto.scalar import reduce_mod_l as _lmodl
    from ba_tpu.crypto.sha512 import sha512 as _sha

    lad_variants = []  # device-resident (points, bits) per variant
    for pk_v, msg_v, sig_v in variants:
        pts, _ = jax.jit(_dec)(pk_v)
        hb = jax.jit(
            lambda s, p, ms: _F.bytes_to_bits(_lmodl(_sha(
                jnp.concatenate([s[..., :32], p, ms], axis=-1)
            )))
        )(sig_v, pk_v, msg_v)
        lad_variants.append((pts, hb))
    lad_fn = jax.jit(
        lambda pt, bits: sum(
            c.astype(jnp.int32).sum() for c in _lmult(pt, bits)
        )
    )
    jax.device_get(lad_fn(*lad_variants[0]))  # compile/warm off the clock
    # Pallas window kernel: 64 windows x (3 dbl@7 + 1 dbl@8 + add@9 muls)
    # + 14 table-build adds.  jnp fallback: 256-step double-and-add-always
    # = 2 complete adds (~8.5 muls each) per bit.
    lad_fmuls_per_lane = (
        64 * 38 + 14 * 9 if _use_pallas() else 256 * 2 * 8.5
    )
    fm_iters = 3
    v_elapsed = fm_elapsed = rlc_elapsed = lad_elapsed = float("inf")
    for r in range(v_reps):
        v_elapsed = min(v_elapsed, _timed(
            lambda *a: vjit(*a),
            lambda i, _r=r: variants[(_r * v_iters + i) % len(variants)],
            v_iters, reps=1,
        ))
        fm_elapsed = min(fm_elapsed, _timed(
            fm_fn,
            lambda i, _r=r: fm_variants[(_r * fm_iters + i) % len(fm_variants)],
            fm_iters, reps=1,
        ))
        rlc_elapsed = min(rlc_elapsed, _timed(
            rlc_fn,
            lambda i, _r=r: (
                *variants[(_r * v_iters + i) % len(variants)],
                z_variants[(_r * v_iters + i) % len(z_variants)],
            ),
            v_iters, reps=1,
        ))
        lad_elapsed = min(lad_elapsed, _timed(
            lad_fn,
            lambda i, _r=r: lad_variants[(_r * v_iters + i) % len(lad_variants)],
            v_iters, reps=1,
        ))
    verifies_per_sec = nv * v_iters / v_elapsed
    rlc_verifies_per_sec = nv * v_iters / rlc_elapsed
    ladder_fieldmuls_per_sec = (
        nv * lad_fmuls_per_lane * v_iters / lad_elapsed
    )
    fieldmul_peak_per_sec = fm_per_dispatch * fm_iters / fm_elapsed

    # (b) the full signed agreement round on-device (verify mask reused —
    # commander signatures are per-(instance, value), already checked).
    sig_valid = jnp.ones((batch, n), bool)

    @jax.jit
    def step(key):  # state/sig_valid closed over: constant across rounds
        out = sm_agreement(key, state, m, None, sig_valid, None, False)
        return out["decision"].astype(jnp.int32).sum()

    key = make_key(3)
    iters = 20
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i),), iters)
    # ~3.66k field muls per verify: 4-bit-window [h]A ladder (~2.56k: 64
    # windows x ~38 muls + 14 table-build adds x 9), 63-add fixed-base
    # [S]B tree (~0.57k), 2 decompression pow-chains (~0.52k), finishing
    # add + projective eq (~0.01k).  Each field mul is 484 int32 limb
    # products + carry/fold shift-adds; the probe chains the same p_mul
    # primitive, so achieved/peak is unit-consistent by construction.
    fmuls_per_verify = 3.66e3
    try:  # XLA's op count from the LOWERED module — pre-compile, so the
        # big verify program is not compiled a second time just for this
        # (an AOT .compile() does not share jit's executable cache and
        # costs ~1 min through the tunnel).
        ca = vjit.lower(*variants[0]).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        xla_flops_per_verify = round(float(ca["flops"]) / nv, 1)
    except Exception:
        xla_flops_per_verify = None
    achieved_fmuls = verifies_per_sec * fmuls_per_verify
    return {
        "xla_flops_per_verify": xla_flops_per_verify,
        "rounds_per_sec": round(batch * iters / elapsed, 1),
        "ed25519_verifies_per_sec": round(verifies_per_sec, 1),
        "rlc_batch_verifies_per_sec": round(rlc_verifies_per_sec, 1),
        "rlc_speedup_vs_per_sig": round(v_elapsed / rlc_elapsed, 2),
        "verify_batch": nv, "batch": batch, "n": n, "m": m,
        "iters": iters, "elapsed_s": round(elapsed, 4),
        "verify_elapsed_s": round(v_elapsed, 4),
        "fieldmuls_per_verify_est": fmuls_per_verify,
        "achieved_fieldmuls_per_sec": round(achieved_fmuls, 1),
        "ladder_fieldmuls_per_sec": round(ladder_fieldmuls_per_sec, 1),
        "chained_pmul_floor_per_sec": round(fieldmul_peak_per_sec, 1),
        "est_int32_gmults_per_sec": round(
            achieved_fmuls * 484 / 1e9, 1
        ),
        "pct_of_ladder_rate": round(
            100 * achieved_fmuls / ladder_fieldmuls_per_sec, 1
        ),
        "bound": "compute (GF(2^255-19) multiplies).  Roofline "
                 "denominator = the 256-bit window-ladder kernel run "
                 "ALONE in the same window (same unit, same code as the "
                 "pipeline's dominant stage, interleaved reps): "
                 "pct_of_ladder_rate <= ~100 by construction, and the "
                 "gap to 100 is the non-ladder stages (sha512, mod-L, "
                 "decompress, fixed-base fold, finish).  "
                 "chained_pmul_floor is a synthetic serial-chain probe "
                 "kept as a lower bound — compound kernels beat it ~2x "
                 "via cross-mul ILP, which is why it is NOT the peak",
    }


def bench_hbm_copy_peak(jax, jnp, jr):
    """Achievable HBM bandwidth via a trivial copy-scale kernel: the
    falsifiable denominator for every "HBM bandwidth" bound claim in this
    suite (VERDICT r3 weak #5: eig_n1024 claimed HBM-bound at 13% of an
    ASSUMED peak).  One int8 read + one int8 write per element over a
    256 MB buffer; content varies per dispatch (tunnel memoization)."""
    size = 1 << 28  # 256 MB
    inner = 48  # barrier-separated passes per dispatch: one pass is ~1 ms
    # of traffic against ~15-100 ms of tunnel dispatch latency, which
    # measured "achievable bandwidth" below what the agreement configs
    # themselves sustain (8 passes still read 112 GB/s, latency-diluted).
    # 48 chained passes put ~24 GB of traffic behind each dispatch.

    @jax.jit
    def f(x):
        # optimization_barrier forces each pass's buffer to MATERIALIZE:
        # without it XLA fuses the whole chain into the reduction and the
        # "copy" never writes a byte (the first cut of this probe
        # reported ~2x real bandwidth that way).  Traffic per pass: read
        # + write; final read for the reduction.
        for _ in range(inner):
            x = jax.lax.optimization_barrier(x ^ jnp.uint8(1))
        return x.sum(dtype=jnp.int32)

    # Pre-staged device variants: uploads must stay out of the timed loop,
    # and EVERY dispatch (1 warm + iters*reps timed) needs distinct bytes
    # — a repeated buffer is served from the tunnel's memo cache.
    iters, reps = 3, 3
    variants = [
        jnp.arange(size, dtype=jnp.uint8) + jnp.uint8(v)
        for v in range(1 + iters * reps)
    ]
    elapsed = _timed(
        f, lambda i: (variants[i % len(variants)],), iters, reps=reps
    )
    gbps = (2 * inner + 1) * size * iters / elapsed / 1e9
    return {
        "achieved_stream_gbps": round(gbps, 1),
        "buffer_mb": size >> 20, "passes_per_dispatch": inner,
        "iters": iters,
        "elapsed_s": round(elapsed, 4),
        "note": "barrier-materialized read+write stream passes "
                "(2*passes+1 bytes/element); the in-window ceiling any "
                "bandwidth-bound config can hope for",
    }


def bench_mxu_int8_peak(jax, jnp, jr, eig_shape=(16, 1024, 1024)):
    """Achievable int8 MXU throughput: the falsifiable same-window
    denominator for every "MXU-bound" claim (VERDICT r4 weak #3:
    eig_n1024's einsum bound shipped with einsum_tmacs_per_sec but NO
    measured denominator).  Same discipline as bench_hbm_copy_peak:
    barrier-chained passes so one dispatch carries enough work to be
    compute-bound, distinct content per dispatch (tunnel memoization).

    Two probes:

    - ``square``: z <- int8((z @ w) & 127), N=2048 — a near-ideal MXU
      shape, the chip-level ceiling estimate;
    - ``eig_shape``: the bij,bjp einsum at eig_n1024's EXACT dims, chained
      through an int8 re-bind of the output — what THIS einsum shape can
      achieve, the denominator pct_of_mxu_peak uses.

    The int32->int8 re-bind between passes fuses into the dot epilogue;
    per-pass HBM traffic is ~2 int8 planes against hundreds of MACs per
    byte, so both probes sit far from the bandwidth roof.
    """
    import numpy as np

    rng = np.random.default_rng(31)
    inner, iters, reps = 24, 3, 3
    n_var = 1 + iters * reps

    def run(f, variants, macs_pass):
        elapsed = _timed(
            f, lambda i: (variants[i % len(variants)],), iters, reps=reps
        )
        return round(macs_pass * inner * iters / elapsed / 1e12, 2), elapsed

    N = 2048
    w = jnp.asarray(rng.integers(-64, 64, (N, N)), jnp.int8)

    @jax.jit
    def f_sq(z):
        for _ in range(inner):
            y = jnp.matmul(z, w, preferred_element_type=jnp.int32)
            z = jax.lax.optimization_barrier((y & 127).astype(jnp.int8))
        return z.sum(dtype=jnp.int32)

    sq_vars = [
        jnp.asarray(rng.integers(-64, 64, (N, N)), jnp.int8)
        for _ in range(n_var)
    ]
    sq_tmacs, sq_el = run(f_sq, sq_vars, N**3)

    B, n, P = eig_shape
    att0 = jnp.asarray(rng.integers(0, 2, (B, n, P)), jnp.int8)

    @jax.jit
    def f_eig(m1):
        att = att0
        for _ in range(inner):
            y = jnp.einsum(
                "bij,bjp->bip", m1, att, preferred_element_type=jnp.int32
            )
            att = jax.lax.optimization_barrier((y & 1).astype(jnp.int8))
        return att.sum(dtype=jnp.int32)

    eig_vars = [
        jnp.asarray(rng.integers(0, 2, (B, n, n)), jnp.int8)
        for _ in range(n_var)
    ]
    eig_tmacs, eig_el = run(f_eig, eig_vars, B * n * n * P)
    return {
        "square_int8_tmacs": sq_tmacs,
        "square_shape": [N, N],
        "eig_shape_int8_tmacs": eig_tmacs,
        "eig_shape": list(eig_shape),
        "passes_per_dispatch": inner,
        "elapsed_s": [round(sq_el, 4), round(eig_el, 4)],
        "note": "barrier-chained int8 matmul/einsum probes; eig_shape_* "
                "is the same-window ceiling for eig_n1024's fused-level "
                "einsum claim",
    }


def bench_eig_n1024(jax, jnp, jr):
    """BASELINE config #4's dense-substrate answer (VERDICT r2 missing #5):
    the EIG tree itself at its single-chip feasible frontier, n=1024.

    r4 re-architecture (core/eig.eig_deepest_fused): the deepest level's
    [B, n, n^2] GiB-scale tensor is never materialized — honest-relay
    tallies are an int8 MXU einsum over the [B, n, n] level-1 tensor and
    traitor coins collapse to Binomial popcount draws — so the config
    stopped being HBM-bound (r3: ~50 rounds/s at an estimated 161 GB/s)
    and m climbs: m=2 matches the r3 config; m=3 (n^3 = 1G paths) is now
    feasible where the dense tree would need a 1 TB tensor.  The dense
    path remains available (BA_TPU_EIG_FUSED=0) and differential-tested.
    A/B'd against the measured copy-kernel bandwidth (bench_hbm_copy_peak)
    so the old bound claim is falsifiable in the same window.

    Batch default 128 (r5, was 16): throughput scales near-linearly with
    batch — 949 / 2481 / 3946 rounds/s at 16/64/128, einsum 1.0 / 2.7 /
    4.2 TMACs/s (EIG_BATCH_r5.json) — and 256 is NOT a chip limit but a
    tunnel one (the remote-compile upload exceeds the endpoint's body
    limit, HTTP 413), so 128 is this backend's single-chip frontier.
    """
    from ba_tpu.core import eig_agreement, make_state
    from ba_tpu.core.types import ATTACK

    n, m = 1024, 2
    batch = int(os.environ.get("BA_TPU_BENCH_EIG1024_BATCH", 128))
    faulty = jnp.zeros((batch, n), bool).at[:, [3, 7]].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)
    max_liars = int(faulty.sum(-1).max())  # derived, never hardcoded

    @jax.jit
    def step(key):  # state closed over: constant across rounds
        out = eig_agreement(key, state, m, max_liars)
        return out["decision"].astype(jnp.int32).sum(), out["needed"].sum()

    key = make_key(8)
    iters = 5
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i),), iters)

    # Stage decomposition of the fused deepest level (VERDICT r4 weak #3:
    # which part actually binds — the MXU einsum or the per-digit
    # corrections?).  Same-window timings of (a) the fused level alone on
    # device-resident inputs and (b) just its mask-build + einsum, both
    # through the step's own internals so the decomposition is honest.
    from ba_tpu.core.eig import eig_deepest_fused, eig_send
    from ba_tpu.core.types import ATTACK as _ATT

    k_lv = make_key(9)
    levels = [jax.device_put(lv) for lv in eig_send(k_lv, state, m - 1)]
    eye = jnp.eye(n, dtype=bool)

    @jax.jit
    def fused_level(key):
        out = eig_deepest_fused(key, state, levels, m, max_liars)
        return out.astype(jnp.int32).sum()

    @jax.jit
    def einsum_only(salt):
        prev = levels[m - 1].reshape(batch, n, n ** (m - 1))
        att = (prev == _ATT).astype(jnp.int8)
        is_leader = jax.nn.one_hot(state.leader, n, dtype=jnp.int8) > 0
        eligible = state.alive & ~is_leader
        m1 = eligible[:, None, :] & (~state.faulty[:, None, :] | eye[None])
        y = jnp.einsum(
            "bij,bjp->bip", m1.astype(jnp.int8), att,
            preferred_element_type=jnp.int32,
        )
        return y.sum() + salt  # salt: distinct dispatch content (memo)

    t_level = _timed(fused_level, lambda i: (jr.fold_in(key, 100 + i),), iters)
    t_einsum = _timed(einsum_only, lambda i: (jnp.int32(i),), iters)
    mxu = bench_mxu_int8_peak(jax, jnp, jr, eig_shape=(batch, n, n ** (m - 1)))
    hbm = bench_hbm_copy_peak(jax, jnp, jr)
    # Fused traffic: the [B, n, n] level-1 tensor (written + read by the
    # einsum), the [B, n, n] popcount words (4B each), einsum output int32.
    bytes_round = batch * n * n * (1 + 1 + 4 + 4)
    macs_round = batch * n * n * n  # the deepest-level int8 einsum
    tmacs = macs_round * iters / elapsed / 1e12
    return {
        "rounds_per_sec": round(batch * iters / elapsed, 1),
        "batch": batch, "n": n, "m": m, "iters": iters,
        "elapsed_s": round(elapsed, 4),
        "bytes_per_round_est": bytes_round,
        "achieved_gbps_est": round(bytes_round * iters / elapsed / 1e9, 2),
        "einsum_tmacs_per_sec": round(tmacs, 3),
        "pct_of_mxu_peak": round(
            100 * tmacs / max(mxu["eig_shape_int8_tmacs"], 1e-9), 1
        ),
        "stages": {
            "full_step_s_per_dispatch": round(elapsed / iters, 4),
            "fused_level_s_per_dispatch": round(t_level / iters, 4),
            "einsum_only_s_per_dispatch": round(t_einsum / iters, 4),
            "note": "fused_level minus einsum_only ~= per-digit "
                    "corrections + popcount draws + majority; full_step "
                    "minus fused_level ~= send levels + shallow resolves",
        },
        "mxu_int8_peak": mxu,
        "hbm_copy_peak": hbm,
        "bound": "MXU int8 einsum + elementwise corrections (fused "
                 "deepest level; the r3 HBM-bound dense form is "
                 "BA_TPU_EIG_FUSED=0); pct_of_mxu_peak now has a "
                 "same-window measured denominator",
    }


def bench_n1024_m32(jax, jnp, jr):
    from ba_tpu.core import make_state, sm_agreement
    from ba_tpu.core.types import ATTACK

    n, m = 1024, 32
    faulty = jnp.zeros((1, n), bool).at[:, :m].set(True)
    state = make_state(1, n, order=ATTACK, faulty=faulty)
    inner = 100  # sequential rounds per dispatch: keeps the TPU-tunnel
    # dispatch latency (tens of ms, high variance) out of the measurement

    @jax.jit
    def step(key):  # state closed over: constant across rounds
        def one(acc, k):
            out = sm_agreement(k, state, m, None, None, None, True)
            return acc + out["decision"].astype(jnp.int32).sum(), None

        acc, _ = jax.lax.scan(one, jnp.int32(0), jr.split(key, inner))
        return acc

    key = make_key(4)
    iters = 5
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i),), iters)
    bytes_round = m * n * 2 * 3  # per relay round: packed-u8 draws + seen bools
    return {
        "rounds_per_sec": round(inner * iters / elapsed, 1),
        "batch": 1, "n": n, "m": m, "iters": inner * iters,
        "elapsed_s": round(elapsed, 4),
        "bytes_per_round_est": bytes_round,
        "bound": "sequential-depth latency (single instance, 32 dependent "
                 "relay rounds/agreement)",
    }


def bench_sweep10k_signed(jax, jnp, jr):
    from ba_tpu.core import sm_agreement
    from ba_tpu.crypto.signed import (
        setup_signed_tables_overlapped,
        warm_signed_tables,
    )
    from ba_tpu.parallel import bucketed_sweep_states

    batch = int(os.environ.get("BA_TPU_BENCH_SWEEP_BATCH", 10240))
    cap, m = 1024, 3
    # Ragged bucketing: equal-count equal-width size buckets, each padded
    # only to its own upper edge (parallel.bucketed_sweep_states) — the
    # n<=512 half of a uniform [4, 1024] sweep stops paying 1024-wide
    # relay lanes.  Same sampled distribution, ~3/4 the padded work at 2
    # buckets.  BA_TPU_BENCH_SWEEP_BUCKETS=1 restores the single flat
    # batch.
    n_buckets = int(os.environ.get("BA_TPU_BENCH_SWEEP_BUCKETS", 2))
    states = bucketed_sweep_states(make_key(5), batch, cap, n_buckets)
    bucket_caps = [int(s.faulty.shape[1]) for s in states]
    bucket_sizes = [int(s.faulty.shape[0]) for s in states]

    # Warm the host signer AND the chunk-shaped verify program before the
    # setup timer: native .so compile, fixed-base window table, and the
    # XLA/Mosaic verify compile are process-lifetime costs (the host-side
    # analogue of the device warmup below).  Per-KEY-SET costs (keygen +
    # 2 signs/instance + table verify) stay on the clock.
    # Chunk default follows the signing substrate: with device signing
    # there is no host/device overlap to exploit, so extra chunks only
    # add dispatch+ACK latency — chunks=1 won the dev-sign column of the
    # same-window A/B (SETUP_AB_r5.json: dev-exact 0.42/0.51/0.70 s at
    # chunks 1/2/4) while 2 remains the host-sign winner (SETUP_AB_r4).
    from ba_tpu.crypto.signed import sign_on_device

    setup_chunks = int(os.environ.get(
        "BA_TPU_BENCH_SETUP_CHUNKS", 1 if sign_on_device() else 2
    ))
    warm_signed_tables(batch, setup_chunks)

    # One-time setup, ON the clock: per-instance keys, 2 signs each, and
    # the device verify of each distinct signature ([B, 2] tables) —
    # chunked so signing chunk c+1 overlaps chunk c's upload+verify on
    # device (VERDICT r3 #1: the sequential form paid sign + verify in
    # full; the residual after the last sign is ``drain_s``).
    sks, pks, msgs_t, sigs_t, ok, setup_t = setup_signed_tables_overlapped(
        batch, chunks=setup_chunks
    )
    setup_sign_s = setup_t["keys_s"] + setup_t["sign_s"]
    # setup_verify_s is the verify cost the setup WALL CLOCK still pays
    # after overlap (the drain residual) — not the device-verify execution
    # time r3 reported under this key; the incl_sign rate below replaces
    # r3's table_verifies_per_sec under a new name so artifact comparisons
    # can't mistake the accounting change for a regression.
    setup_verify_s = setup_t["drain_s"]
    setup_verifies_per_sec_incl_sign = 2 * batch / setup_t["total_s"]

    # The timed step is the whole per-round signed pipeline on device:
    # round-1 equivocation broadcast -> per-copy signature-mask gather from
    # the verified tables -> m collapsed relay rounds -> quorum.
    from ba_tpu.core.om import round1_broadcast
    from ba_tpu.crypto.signed import sig_valid_from_tables

    # Per-bucket slices of the verified signature tables (instances were
    # sampled bucket-major, so the key/table order matches concatenation
    # order of the bucket states).
    oks = []
    off = 0
    for bk in bucket_sizes:
        oks.append(ok[off : off + bk])
        off += bk

    # BA_TPU_FUSED_SWEEP: 1 = the single-Pallas-kernel step (in-kernel
    # hardware PRNG, whole round in VMEM — ops/sweep_step.py), 0 = the XLA
    # composition, auto (default) = fused wherever the Pallas kernels are
    # on.  Hardware-validated r3: 5/5 differential tests on chip
    # (TESTS_TPU_FUSED_r3.txt) and a same-window +28% over the XLA path
    # (FUSED_AB_r3.json).
    from ba_tpu.utils.platform import use_pallas

    fused_env = os.environ.get("BA_TPU_FUSED_SWEEP", "auto")
    use_fused = fused_env == "1" or (fused_env == "auto" and use_pallas())
    # Rounds per fused dispatch (BA_TPU_FUSED_ROUNDS): the state planes
    # stay VMEM-resident and the per-dispatch overhead divides by K
    # (ops/sweep_step.py multi-round kernel).  Dispatch overhead dominates
    # through K=15 and the marginal per-round cost flattens past K~30
    # (ROUNDS_AB_r4.json: 2.2M at K=1 -> 24.7M/31.2M/37.3M/43.4M rounds/s
    # at K=15/30/60/120 same-window).  r5's in-kernel round loop made
    # compile cost O(1) in K (the r4 unrolled trace hit a >25 min compile
    # frontier at K=240), so K is purely a batching dial now: the r5
    # ladder runs 39.8M/45.3M/48.6M/50.4M/51.3M rounds/s at
    # K=60/120/240/480/960 same-window (ROUNDS_AB_r5.json), so the
    # default sits at 480 — within ~2% of the K=960 asymptote while one
    # dispatch stays under 0.1 s.  The XLA path is one round per call,
    # so K applies only when fused.
    fused_rounds = int(os.environ.get("BA_TPU_FUSED_ROUNDS", 480))
    rounds_per_step = fused_rounds if use_fused else 1
    if use_fused:
        from ba_tpu.ops.sweep_step import fused_signed_sweep_step

        def one_bucket(key, state, ok):
            seed = jax.lax.bitcast_convert_type(
                jr.key_data(key)[-1:], jnp.int32
            )
            dec = fused_signed_sweep_step(
                seed, state.order, state.leader, state.faulty, state.alive,
                ok, m, fused_rounds,
            )
            return dec.astype(jnp.int32).sum()
    else:
        def one_bucket(key, state, ok):
            k1, k2 = jr.split(key)
            received = round1_broadcast(k1, state)
            sig_valid = sig_valid_from_tables(ok, received)
            out = sm_agreement(k2, state, m, None, sig_valid, received, True)
            return out["decision"].astype(jnp.int32).sum()

    # states/oks are per-key-set constants: close over them so each timed
    # dispatch ships ONE key instead of ~20 array handles.  Two effects,
    # both of which a real campaign amortizes identically (state is built
    # once and stepped thousands of times, examples/sweep_campaign.py):
    # per-dispatch argument processing through the tunnel goes away, and
    # XLA may constant-fold the state pad/astype prep out of the step.
    # Measured r3: 2.8M rounds/s seed-only vs 1.35M args-per-call in the
    # same window.
    @jax.jit
    def step(key):
        acc = jnp.int32(0)
        for i, (st, okb) in enumerate(zip(states, oks)):
            acc += one_bucket(jr.fold_in(key, i), st, okb)
        return acc

    key = make_key(6)
    iters = 50
    elapsed = _timed(step, lambda i: (jr.fold_in(key, i),), iters)
    # Per round: m packed-u8 draw cubes [B, cap_bucket, 2] + seen rows.
    lane_rows = sum(b * c for b, c in zip(bucket_sizes, bucket_caps))
    bytes_round = lane_rows * (m * 2 + 8)
    rounds_per_iter = batch * rounds_per_step
    rps = rounds_per_iter * iters / elapsed
    # The honest north-star accounting (VERDICT r2 missing #1): a fresh
    # key-set pays setup (keygen + host signing + the device table-verify,
    # overlapped) before any round runs, so report rounds/s *including*
    # setup at stated amortization horizons, plus the horizon where the
    # including-setup rate crosses the 1M target.  An "iteration" here is
    # one dispatch = rounds_per_step agreement rounds per instance.
    setup_total = setup_t["total_s"]
    t_iter = elapsed / iters
    incl = {
        f"h{h}": round(rounds_per_iter * h / (setup_total + h * t_iter), 1)
        for h in (50, 100, 500, 5000)
    }
    if rounds_per_iter / 1e6 > t_iter:
        crossover = setup_total / (rounds_per_iter / 1e6 - t_iter)
        crossover_iters = int(crossover) + 1
    else:
        crossover_iters = None  # never crosses at this throughput
    return {
        "rounds_per_sec": round(rps, 1),
        "vs_target_1M": round(rps / 1e6, 3),
        "batch": batch, "n_max": cap, "m": m, "iters": iters,
        "buckets": [
            {"instances": b, "padded_n": c}
            for b, c in zip(bucket_sizes, bucket_caps)
        ],
        "fused_kernel": use_fused,
        "fused_rounds_per_dispatch": rounds_per_step,
        "elapsed_s": round(elapsed, 4),
        "setup_sign_s": round(setup_sign_s, 2),
        "setup_verify_s": round(setup_verify_s, 2),
        "setup_total_s": round(setup_total, 2),
        "setup_chunks": setup_t["chunks"],
        "setup_device_sign": setup_t.get("device_sign", False),
        "setup_verifies_per_sec_incl_sign": round(
            setup_verifies_per_sec_incl_sign, 1
        ),
        "setup_congestion_note": "in-suite setup drains behind the whole "
            "bench queue, so setup_verify_s here rides window congestion; "
            "standalone same-window measurements put the drain residual "
            "at 0.08-0.10 s (SETUP_AB_r4.json) — compare setups via the "
            "SETUP_AB artifacts, not this in-suite figure",
        "rounds_per_sec_incl_setup": incl,
        "incl_setup_crossover_1M_iters": crossover_iters,
        "bytes_per_round_est": bytes_round,
        "achieved_gbps_est": round(
            bytes_round * rounds_per_step * iters / elapsed / 1e9, 2
        ),
        "bound": "VPU throughput (packed-u8 RNG + elementwise relay; "
                 "far from HBM peak)",
        "note": "signing+table-verify are one-time setup per key-set, "
                "host-sign overlapped with device verify "
                "(setup_verify_s = the un-overlapped drain residual); "
                "rounds_per_sec_incl_setup charges setup_total_s at each "
                "horizon H of fused-rounds dispatches",
    }


def bench_pipeline_sweep(jax, jnp, jr):
    """The pipelined multi-round engine vs the blocking per-round driver,
    SAME round count, same-window interleaved reps (ISSUE 1 tentpole).

    Blocking driver = the inherited disease in miniature: a host-side
    ``jr.split`` per round to derive keys, a fresh key upload per
    dispatch, and a ``jax.device_get`` fetch before the next round may be
    dispatched — host and device strictly alternate.  Pipelined driver =
    ``parallel.pipeline.pipeline_sweep``: the key schedule lives on
    device (folded counter), the state and schedule buffers are donated
    so steady-state rounds allocate nothing, K rounds ride per dispatch
    in a ``lax.scan`` megastep, and up to ``depth`` dispatches stay in
    flight with the only blocking operation being the depth-delayed
    retire of a 3-int histogram.

    The dispatch/retire schedule is verified structurally (the engine's
    stats + tests/test_pipeline.py's no-intermediate-blocking test); this
    config measures what that structure buys in wall clock.  Both drivers
    consume identical instance states; per-rep state copies for the
    donating engine are staged off the clock.
    """
    from ba_tpu.parallel import make_sweep_state
    from ba_tpu.parallel.pipeline import fresh_copy, pipeline_sweep
    from ba_tpu.parallel.sweep import agreement_step

    batch = int(os.environ.get("BA_TPU_BENCH_PIPE_BATCH", 2048))
    cap = int(os.environ.get("BA_TPU_BENCH_PIPE_CAP", 64))
    rounds = int(os.environ.get("BA_TPU_BENCH_PIPE_ROUNDS", 64))
    depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
    per_dispatch = int(os.environ.get("BA_TPU_BENCH_PIPE_KPD", 8))
    unroll = int(os.environ.get("BA_TPU_BENCH_PIPE_UNROLL", 2))
    m = 1
    state = make_sweep_state(make_key(20), batch, cap)

    # Blocking per-round driver.  Keys are split on the HOST each round
    # and the histogram is fetched before the next dispatch — the exact
    # shape of the reference's poll-per-round loop, minus the 0.1 s tick.
    step = jax.jit(agreement_step, static_argnames=("m", "max_liars"))
    key = make_key(21)

    def run_blocking(k):
        hists = []
        for _ in range(rounds):
            k, sub = jr.split(k)
            out = step(jr.split(sub, batch), state, m=m)
            hists.append(jax.device_get(out["histogram"]))
        return hists

    reps = 3
    # Donation consumes the engine's input state: stage one copy per rep
    # (plus warmup) off the clock.  The blocking driver reuses `state`
    # (it never donates).
    states = [fresh_copy(state) for _ in range(reps + 1)]

    def run_pipelined(k, st):
        return pipeline_sweep(
            k, st, rounds,
            m=m, depth=depth, rounds_per_dispatch=per_dispatch,
            unroll=unroll,
        )

    # Warm/compile both off the clock, then interleave reps so the two
    # sides share one service window (tunnel drift cancels).
    run_blocking(jr.fold_in(key, 0))
    run_pipelined(jr.fold_in(key, 1), states[0])
    t_block = t_pipe = float("inf")
    for r in range(reps):
        t0 = time.perf_counter()
        run_blocking(jr.fold_in(key, 2 + 2 * r))
        t_block = min(t_block, time.perf_counter() - t0)
        t0 = time.perf_counter()
        out = run_pipelined(jr.fold_in(key, 3 + 2 * r), states[1 + r])
        t_pipe = min(t_pipe, time.perf_counter() - t0)
    stats = out["stats"]
    rps_pipe = batch * rounds / t_pipe
    rps_block = batch * rounds / t_block
    # Device-tier cost/memory (ISSUE 4): under --obs the engine's first
    # compile AOT-harvested the megastep's XLA analysis into gauges
    # (obs/xla.py) — surface them in the config artifact so the
    # flops/bytes/donation-alias evidence rides next to the wall-clock
    # numbers it explains.  Empty (and omitted) when --obs is off.
    from ba_tpu import obs

    xla_cost = {
        name[len("xla_pipeline_megastep_"):]: snap["value"]
        for name, snap in obs.default_registry().snapshot().items()
        if name.startswith("xla_pipeline_megastep_")
    }
    result_extra = {"xla_cost": xla_cost} if xla_cost else {}
    return {
        **result_extra,
        "rounds_per_sec": round(rps_pipe, 1),
        "blocking_rounds_per_sec": round(rps_block, 1),
        "pipeline_speedup_vs_blocking": round(t_block / t_pipe, 2),
        "batch": batch, "n_max": cap, "m": m, "rounds": rounds,
        "depth": depth,
        "rounds_per_dispatch": per_dispatch,
        "scan_unroll": unroll,
        "dispatches": stats["dispatches"],
        "max_in_flight": stats["max_in_flight"],
        "retires_before_drain": stats["retires_before_drain"],
        "elapsed_s": round(t_pipe, 4),
        "blocking_elapsed_s": round(t_block, 4),
        "bound": "per-dispatch overhead amortization: the blocking side "
                 "pays (host key split + upload + fetch sync) x rounds; "
                 "the pipelined side pays dispatches = ceil(rounds/K) "
                 "async dispatches with donated steady-state buffers and "
                 "an on-device key schedule",
        "note": "same-window interleaved A/B at EQUAL round count; "
                "steady-state host syncs are the depth-delayed histogram "
                "retires only (no block_until_ready anywhere — enforced "
                "by scripts/ci.sh's hot-path lint + the dispatch-count "
                "test)",
    }


def bench_scenario_sweep(jax, jnp, jr):
    """The pipelined MUTATING campaign (scenario engine, ISSUE 5) vs the
    old sequential failover driver, SAME campaign, same-window
    interleaved reps.

    Sequential driver = what running this campaign looked like before
    the scenario engine: one jitted kill -> re-elect -> strategy-aware
    agree + counter-fold step per round, a host-side ``jr.split`` per
    round for the keys, and a ``jax.device_get`` fetch of the round's
    histogram/leader/counter outputs before the next round may be
    dispatched — host and device strictly alternate (the reference's
    poll-per-round loop, plus mutation).  Pipelined driver =
    ``pipeline_sweep(scenario=...)``: the same kill schedule compiled
    ONCE to dense planes, K mutating rounds per donated ``lax.scan``
    dispatch, membership/election/strategy state riding the donated
    carry, depth-k dispatches in flight, and the only sync the
    depth-delayed retire of the histogram/leader/counter block.

    BOTH sides compute the identical per-round outputs (strategy-aware
    step, 5-entry scenario counter block incl. IC1/IC2 verdicts,
    per-round leaders) from identical states and the identical
    ~2%/round crash schedule, so the measured delta is pure driver
    structure — per-round host sync + per-round key upload vs async
    donated megasteps.  Per-rep state copies for the donating engine
    are staged off the clock.
    """
    import numpy as np

    from ba_tpu.core.election import elect_lowest_id
    from ba_tpu.core.state import SimState
    from ba_tpu.parallel import make_sweep_state
    from ba_tpu.parallel.pipeline import (
        fresh_copy,
        pipeline_sweep,
        scenario_counter_delta,
        scenario_counters_init,
    )
    from ba_tpu.parallel.sweep import agreement_step
    from ba_tpu.scenario.compile import block_from_kills

    batch = int(os.environ.get("BA_TPU_BENCH_SCEN_BATCH", 2048))
    cap = int(os.environ.get("BA_TPU_BENCH_SCEN_CAP", 64))
    rounds = int(os.environ.get("BA_TPU_BENCH_SCEN_ROUNDS", 64))
    depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
    per_dispatch = int(os.environ.get("BA_TPU_BENCH_SCEN_KPD", 8))
    unroll = int(os.environ.get("BA_TPU_BENCH_SCEN_UNROLL", 2))
    m = 1
    state = make_sweep_state(make_key(30), batch, cap)
    rng = np.random.default_rng(31)
    kills_np = rng.random((rounds, batch, cap)) < 0.02
    block = block_from_kills(kills_np)
    kills_dev = jnp.asarray(kills_np)  # staged once, off the clock
    strategy0 = jnp.zeros((batch, cap), jnp.int8)

    # Sequential failover driver: the per-round step is on-device and
    # computes EXACTLY what one scenario-engine round computes, but the
    # LOOP is host-driven — split, dispatch, fetch, repeat.
    @jax.jit
    def seq_step(keys, leader, alive, counters, kill, strategy):
        alive = alive & ~kill
        dead = ~jnp.take_along_axis(alive, leader[:, None], axis=1)[:, 0]
        leader = jnp.where(dead, elect_lowest_id(state.ids, alive), leader)
        st = SimState(state.order, leader, state.faulty, alive, state.ids)
        out = agreement_step(keys, st, m=m, strategies=strategy)
        counters = counters + scenario_counter_delta(out, st)
        return leader, alive, counters, out["histogram"]

    def run_sequential(k):
        leader, alive = state.leader, state.alive
        counters = scenario_counters_init()
        fetched = []
        for r in range(rounds):
            k, sub = jr.split(k)
            leader, alive, counters, hist = seq_step(
                jr.split(sub, batch), leader, alive, counters,
                kills_dev[r], strategy0,
            )
            # Blocks every round: the same histogram/leader/counter
            # block the pipelined engine only fetches at retire time.
            fetched.append(jax.device_get((hist, leader, counters)))
        return fetched

    def run_pipelined(k, st):
        return pipeline_sweep(
            k, st, rounds,
            m=m, depth=depth, rounds_per_dispatch=per_dispatch,
            unroll=unroll, scenario=block,
        )

    key = make_key(32)
    reps = 3
    states = [fresh_copy(state) for _ in range(reps + 1)]
    run_sequential(jr.fold_in(key, 0))  # compile/warm off the clock
    out = run_pipelined(jr.fold_in(key, 1), states[0])
    t_seq = t_pipe = float("inf")
    for r in range(reps):  # interleaved: window drift cancels
        t0 = time.perf_counter()
        run_sequential(jr.fold_in(key, 2 + 2 * r))
        t_seq = min(t_seq, time.perf_counter() - t0)
        t0 = time.perf_counter()
        out = run_pipelined(jr.fold_in(key, 3 + 2 * r), states[1 + r])
        t_pipe = min(t_pipe, time.perf_counter() - t0)
    stats = out["stats"]
    return {
        "rounds_per_sec": round(batch * rounds / t_pipe, 1),
        "sequential_rounds_per_sec": round(batch * rounds / t_seq, 1),
        "pipeline_speedup_vs_sequential": round(t_seq / t_pipe, 2),
        "batch": batch, "n_max": cap, "m": m, "rounds": rounds,
        "depth": depth,
        "rounds_per_dispatch": per_dispatch,
        "scan_unroll": unroll,
        "dispatches": stats["dispatches"],
        "max_in_flight": stats["max_in_flight"],
        "kill_prob_per_round": 0.02,
        "scenario_counters": out["counters"],
        "elapsed_s": round(t_pipe, 4),
        "sequential_elapsed_s": round(t_seq, 4),
        "bound": "per-dispatch overhead amortization, now WITH mutation: "
                 "the sequential side pays (host key split + upload + "
                 "fetch sync) x rounds around the identical kill/elect/"
                 "agree/count step; the scenario engine pays "
                 "ceil(rounds/K) async donated dispatches with the event "
                 "planes compiled once and the membership/election/"
                 "strategy state riding the carry",
        "note": "same-window interleaved A/B; both sides compute the "
                "identical strategy-aware rounds, 5-entry scenario "
                "counter block (incl. IC1/IC2 verdicts) and per-round "
                "leaders from the same states and kill schedule, so the "
                "delta is pure driver structure.  CPU artifact "
                "BENCH_scenario_r8.json; the tunnel re-run is a ROADMAP "
                "follow-on",
    }


def bench_scenario_long(jax, jnp, jr):
    """Streaming long-campaign config (ISSUE 6 acceptance): a >=100k-round
    SPARSE campaign — R far beyond what dense lowering can allocate at
    production batch — at steady-state rounds/s within 10% of the
    equivalent dense-lowered SHORT campaign, with peak host plane bytes
    bounded by the CHUNK size, not R.

    The long side lowers sparse (``compile_scenario(sparse=True)``):
    host memory is O(events), chunks materialize per dispatch
    double-buffered in the overlap slot, and the mostly-empty stretches
    reuse one staged zero chunk.  The short side is the same campaign
    cadence dense-lowered at a round count dense CAN afford — same
    (batch, capacity, rounds_per_dispatch) specialization, so the
    per-round compiled program is identical and the measured delta is
    pure staging structure.  Campaign cadence: every ``churn`` rounds
    the current leader is killed and the previous one revived (leader
    bounces 1 <-> 2, elections churn for the whole campaign), plus one
    mid-campaign fault+strategy flip — the reference's detect->elect
    production loop (ba.py's run thread) at soak-test length.

    The not-allocatable claim is reported as numbers, not prose:
    ``dense_equiv_plane_bytes`` (this shape) and
    ``dense_equiv_plane_bytes_at_scenario_sweep_shape`` (the engine's
    production config, B=2048 n=64 — half a terabyte at R = 1e6).
    """
    from ba_tpu.parallel import fresh_copy, make_sweep_state, scenario_sweep
    from ba_tpu.scenario import compile_scenario, from_dict

    batch = int(os.environ.get("BA_TPU_BENCH_LONG_BATCH", 64))
    cap = int(os.environ.get("BA_TPU_BENCH_LONG_CAP", 8))
    r_long = int(os.environ.get("BA_TPU_BENCH_LONG_ROUNDS", 250_000))
    r_short = int(os.environ.get("BA_TPU_BENCH_LONG_SHORT_ROUNDS", 8192))
    per_dispatch = int(os.environ.get("BA_TPU_BENCH_LONG_KPD", 512))
    depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
    reps = int(os.environ.get("BA_TPU_BENCH_LONG_REPS", 1))
    m = 1

    def churn_spec(rounds, churn):
        # Leader bounce: odd churn ticks kill G1 / revive G2, even ticks
        # kill G2 / revive G1 — every tick is a death-detect-re-elect
        # transition, the soak shape of the reference's run loop.
        events = []
        k = 0
        for r in range(churn, rounds, churn):
            k += 1
            a, b = (1, 2) if k % 2 else (2, 1)
            events.append({"round": r, "kill": [a]})
            events.append({"round": r, "revive": [b]})
        events.append(
            {"round": rounds // 2, "set_faulty": [3], "value": True}
        )
        events.append(
            {"round": rounds // 2, "set_strategy": [3], "value": "silent"}
        )
        return from_dict(
            {"name": f"churn-{rounds}", "rounds": rounds, "order": "attack",
             "events": sorted(events, key=lambda e: e["round"])}
        )

    # IDENTICAL churn interval in rounds on both sides — hence the same
    # fraction of event-bearing dispatches — so the measured delta is
    # staging structure, not a lighter event diet on one side.  The
    # interval is sized off the SHORT campaign (an event every other
    # dispatch at the defaults) and reused verbatim for the long one.
    churn = max(per_dispatch, (r_short // 8) // per_dispatch * per_dispatch)
    sparse_block = compile_scenario(
        churn_spec(r_long, churn), batch, cap, sparse=True
    )
    dense_block = compile_scenario(churn_spec(r_short, churn), batch, cap)
    state = make_sweep_state(make_key(40), batch, cap)
    key = make_key(41)

    def run(k, st, block):
        return scenario_sweep(
            k, st, block,
            m=m, depth=depth, rounds_per_dispatch=per_dispatch,
        )

    # Warm EVERY specialization either side will dispatch, off the
    # clock: the full-chunk megastep AND the ragged-remainder chunks
    # (r % K).  A remainder specialization compiling inside the timed
    # long run costs ~0.5 s on CPU — 20%+ of phantom "staging overhead"
    # in the first cut of this config.
    for i, rem in enumerate(
        sorted({0, r_long % per_dispatch, r_short % per_dispatch})
    ):
        warm_block = compile_scenario(
            churn_spec(2 * per_dispatch + rem, per_dispatch),
            batch, cap, sparse=True,
        )
        run(jr.fold_in(key, 100 + i), fresh_copy(state), warm_block)

    t_short = t_long = float("inf")
    out_long = None
    for r in range(reps):
        t0 = time.perf_counter()  # short leg brackets the long one so
        run(jr.fold_in(key, 2 + 3 * r), fresh_copy(state), dense_block)
        t_short = min(t_short, time.perf_counter() - t0)
        t0 = time.perf_counter()
        out_long = run(jr.fold_in(key, 3 + 3 * r), fresh_copy(state),
                       sparse_block)
        t_long = min(t_long, time.perf_counter() - t0)
        t0 = time.perf_counter()  # ...window drift shows up as the
        run(jr.fold_in(key, 4 + 3 * r), fresh_copy(state), dense_block)
        t_short = min(t_short, time.perf_counter() - t0)

    stats = out_long["stats"]
    rps_long = batch * r_long / t_long
    rps_short = batch * r_short / t_short
    chunk_bound = per_dispatch * batch * cap * 4  # 4 packed planes
    return {
        "rounds_per_sec": round(rps_long, 1),
        "dense_short_rounds_per_sec": round(rps_short, 1),
        "sparse_vs_dense_ratio": round(rps_long / rps_short, 3),
        "within_10pct": rps_long >= 0.9 * rps_short,
        "batch": batch, "n_max": cap, "m": m,
        "rounds_long": r_long, "rounds_short": r_short,
        "rounds_per_dispatch": per_dispatch, "depth": depth,
        "dispatches": stats["dispatches"],
        "max_in_flight": stats["max_in_flight"],
        "checkpoints": stats["checkpoints"],
        "peak_host_plane_bytes": stats["plane_peak_bytes"],
        "chunk_plane_bytes_bound": chunk_bound,
        "plane_bytes_bounded_by_chunk": stats["plane_peak_bytes"]
        <= chunk_bound,
        "stage_overlap_s": stats["stage_s"],
        "event_rounds": len(sparse_block.event_rounds),
        "dense_equiv_plane_bytes": r_long * batch * cap * 4,
        "dense_equiv_plane_bytes_at_scenario_sweep_shape":
            r_long * 2048 * 64 * 4,
        "elapsed_s": round(t_long, 4),
        "dense_short_elapsed_s": round(t_short, 4),
        "scenario_counters": out_long["counters"],
        "bound": "same compiled megastep on both sides; the delta is "
                 "staging structure — the dense side re-uploads full "
                 "event chunks every dispatch, the sparse side stages "
                 "O(chunk) planes double-buffered and reuses one zero "
                 "chunk across the empty stretches",
        "note": "long side is min-of-%d; short side min over the two "
                "legs bracketing each long run (same-window).  Dense "
                "lowering at this R would allocate "
                "dense_equiv_plane_bytes on host AND device-stage it; "
                "at the scenario_sweep production shape it is "
                "dense_equiv_plane_bytes_at_scenario_sweep_shape — the "
                "memory wall the sparse encoding removes" % reps,
    }


def bench_resilience(jax, jnp, jr):
    """Resilient-execution config (ISSUE 7 acceptance): what does
    surviving faults COST?  Four legs over the identical churn campaign
    (same keys, same spec, same engine dials — every leg's decisions are
    bit-identical, asserted):

    1. ``plain`` — the uninterrupted, unsupervised baseline
       (``scenario_sweep``, no checkpoints).
    2. ``supervised`` — the execution supervisor live (watchdog armed,
       seam installed, rows collection + carry checkpoints every
       ``rounds_per_dispatch x depth`` rounds ≈ one dispatch depth):
       the DURABILITY tax.
    3. ``recovery`` — leg 2 plus an injected FATAL fault mid-campaign:
       the supervisor resumes from the newest checkpoint and replays
       the gap; ``recovery_overhead_frac`` (vs leg 1) is the pinned
       <= 15% acceptance number.
    4. ``kill`` (once, reported separately — it pays a fresh python +
       jax + compile start, which is process-replacement cost, not
       engine overhead) — a chaos ``kill`` fault SIGKILLs a child
       process mid-campaign; rerunning the same supervised call in THIS
       process auto-resumes from the child's checkpoint + rows sidecar
       and completes; the assembled result is bit-identical to leg 1,
       and ``kill_lost_rounds`` counts the re-executed window.
    """
    import shutil
    import signal
    import subprocess
    import tempfile

    import numpy as np

    from ba_tpu.parallel import fresh_copy, make_sweep_state, scenario_sweep
    from ba_tpu.runtime import chaos as chaos_mod
    from ba_tpu.runtime.supervisor import SupervisorConfig, supervised_sweep
    from ba_tpu.scenario import compile_scenario, from_dict
    from ba_tpu.utils import snapshot as _snapshot

    batch = int(os.environ.get("BA_TPU_BENCH_RES_BATCH", 64))
    cap = int(os.environ.get("BA_TPU_BENCH_RES_CAP", 8))
    rounds = int(os.environ.get("BA_TPU_BENCH_RES_ROUNDS", 32768))
    per_dispatch = int(os.environ.get("BA_TPU_BENCH_RES_KPD", 256))
    depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
    reps = int(os.environ.get("BA_TPU_BENCH_RES_REPS", 3))
    ckpt_every = per_dispatch * depth  # ≈ one dispatch depth of rounds
    fatal_round = rounds // 2
    kill_round = (5 * rounds // 8) // per_dispatch * per_dispatch
    m = 1

    # The same churn cadence as scenario_long, at resilience scale: a
    # leader bounce every 4 dispatches plus one mid-campaign fault flip.
    events = []
    k = 0
    for r in range(4 * per_dispatch, rounds, 4 * per_dispatch):
        k += 1
        a, b = (1, 2) if k % 2 else (2, 1)
        events.append({"round": r, "kill": [a]})
        events.append({"round": r, "revive": [b]})
    events.append({"round": rounds // 2, "set_faulty": [3], "value": True})
    spec_doc = {
        "name": "resilience-churn", "rounds": rounds, "order": "attack",
        "events": sorted(events, key=lambda e: e["round"]),
    }
    block = compile_scenario(from_dict(spec_doc), batch, cap, sparse=True)
    state = make_sweep_state(make_key(50), batch, cap)
    key = make_key(51)
    cfg = SupervisorConfig(timeout_s=300.0, backoff_base_s=0.0)

    def plain(k):
        return scenario_sweep(
            k, fresh_copy(state), block,
            m=m, depth=depth, rounds_per_dispatch=per_dispatch,
            collect_decisions=True,
        )

    def supervised(k, ckdir, plan=None):
        return supervised_sweep(
            k, fresh_copy(state), scenario=block,
            m=m, depth=depth, rounds_per_dispatch=per_dispatch,
            collect_decisions=True, config=cfg,
            chaos=None if plan is None else chaos_mod.ChaosInjector(plan),
            checkpoint_every=ckpt_every,
            checkpoint_path=os.path.join(ckdir, "res_{round}.npz"),
        )

    fatal_plan = chaos_mod.from_dict(
        {"name": "bench-fatal",
         "faults": [{"round": fatal_round, "kind": "fatal"}]}
    )

    # Warm every specialization off the clock (full chunk + remainder,
    # plain and supervised paths share them).
    out_ref = plain(key)
    with tempfile.TemporaryDirectory() as td:
        supervised(key, td)

    # Per-rep times, kept PAIRED: host CPU throughput drifts between
    # reps (shared box), so the overhead estimator is the median of the
    # per-rep ratios — each rep's supervised/recovery legs divide by
    # that same rep's plain leg, cancelling drift that a min-of-reps
    # over independent legs would fold into the comparison.
    plains, sups, recs = [], [], []
    out_sup = out_rec = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out_plain = plain(key)
        plains.append(time.perf_counter() - t0)
        ckdir = tempfile.mkdtemp(prefix="ba_res_sup_")
        try:
            t0 = time.perf_counter()
            out_sup = supervised(key, ckdir)
            sups.append(time.perf_counter() - t0)
        finally:
            shutil.rmtree(ckdir, ignore_errors=True)
        ckdir = tempfile.mkdtemp(prefix="ba_res_rec_")
        try:
            t0 = time.perf_counter()
            out_rec = supervised(key, ckdir, fatal_plan)
            recs.append(time.perf_counter() - t0)
        finally:
            shutil.rmtree(ckdir, ignore_errors=True)
    t_plain, t_sup, t_rec = min(plains), min(sups), min(recs)
    sup_frac = sorted(s / p - 1 for s, p in zip(sups, plains))[reps // 2]
    rec_frac = sorted(r / p - 1 for r, p in zip(recs, plains))[reps // 2]

    # Every leg computed the SAME campaign, bit-exactly.
    for out in (out_plain, out_sup, out_rec):
        np.testing.assert_array_equal(out["decisions"], out_ref["decisions"])
        np.testing.assert_array_equal(out["leaders"], out_ref["leaders"])
        assert out["counters"] == out_ref["counters"]
    assert out_rec["supervisor"]["recoveries"] == 1

    # Leg 4: the real preemption — SIGKILL a child mid-campaign, then
    # auto-resume HERE from its newest checkpoint + rows sidecar.
    kill_dir = tempfile.mkdtemp(prefix="ba_res_kill_")
    kill_result = {}
    try:
        ck_tmpl = os.path.join(kill_dir, "res_{round}.npz")
        child = f"""
import os
from ba_tpu.core.rng import make_key
from ba_tpu.parallel import fresh_copy, make_sweep_state
from ba_tpu.runtime import chaos
from ba_tpu.runtime.supervisor import SupervisorConfig, supervised_sweep
from ba_tpu.scenario import compile_scenario, from_dict

block = compile_scenario(
    from_dict({spec_doc!r}), {batch}, {cap}, sparse=True
)
state = make_sweep_state(make_key(50), {batch}, {cap})
plan = chaos.from_dict({{
    "name": "bench-kill",
    "faults": [{{"round": {kill_round}, "kind": "kill"}}],
}})
supervised_sweep(
    make_key(51), state, scenario=block,
    m={m}, depth={depth}, rounds_per_dispatch={per_dispatch},
    collect_decisions=True, chaos=plan,
    config=SupervisorConfig(timeout_s=300.0),
    checkpoint_every={ckpt_every}, checkpoint_path={ck_tmpl!r},
)
raise SystemExit("unreachable: the kill fault must have fired")
"""
        env = dict(os.environ)
        platform = os.environ.get("BA_TPU_BENCH_PLATFORM")
        if platform:
            env["JAX_PLATFORMS"] = platform
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-c", child],
            capture_output=True, text=True, timeout=1800, env=env,
        )
        t_child = time.perf_counter() - t0
        assert proc.returncode == -signal.SIGKILL, (
            proc.stdout + proc.stderr
        )
        found = _snapshot.newest_valid_checkpoint(ck_tmpl)
        assert found is not None, "the child died before any checkpoint"
        resumed_from = found[1]["round"]
        t0 = time.perf_counter()
        out_kill = supervised_sweep(
            key, fresh_copy(state), scenario=block,
            m=m, depth=depth, rounds_per_dispatch=per_dispatch,
            collect_decisions=True, config=cfg,
            checkpoint_every=ckpt_every, checkpoint_path=ck_tmpl,
        )
        t_resume = time.perf_counter() - t0
        np.testing.assert_array_equal(
            out_kill["decisions"], out_ref["decisions"]
        )
        np.testing.assert_array_equal(
            out_kill["leaders"], out_ref["leaders"]
        )
        assert out_kill["counters"] == out_ref["counters"]
        assert out_kill["supervisor"]["history_start"] == 0
        kill_result = {
            "kill_round": kill_round,
            "kill_resumed_from_round": resumed_from,
            "kill_lost_rounds": kill_round - resumed_from,
            "kill_child_wall_s": round(t_child, 4),
            "kill_resume_wall_s": round(t_resume, 4),
            "kill_bit_identical": True,
        }
    finally:
        shutil.rmtree(kill_dir, ignore_errors=True)

    return {
        "rounds_per_sec": round(batch * rounds / t_plain, 1),
        "batch": batch, "n_max": cap, "m": m, "rounds": rounds,
        "rounds_per_dispatch": per_dispatch, "depth": depth,
        "checkpoint_every": ckpt_every,
        "checkpoints": out_sup["stats"]["checkpoints"],
        "plain_elapsed_s": round(t_plain, 4),
        "supervised_elapsed_s": round(t_sup, 4),
        "recovery_elapsed_s": round(t_rec, 4),
        "supervised_overhead_frac": round(sup_frac, 4),
        "recovery_overhead_frac": round(rec_frac, 4),
        "recovery_within_15pct": rec_frac <= 0.15,
        "fatal_round": fatal_round,
        "recovery_lost_rounds": out_rec["supervisor"]["lost_rounds"],
        "recoveries": out_rec["supervisor"]["recoveries"],
        "timeout_s": out_rec["supervisor"]["timeout_s"],
        **kill_result,
        "bound": "every leg computes the identical campaign bit-exactly "
                 "(asserted); the supervised delta is checkpoint + rows-"
                 "sidecar serialization inside the existing retire sync, "
                 "and the recovery delta adds one newest-valid-checkpoint "
                 "scan plus replay of the window between the last "
                 "checkpoint and the fault",
        "note": "elapsed = min of %d interleaved reps; overhead fracs = "
                "MEDIAN of per-rep PAIRED ratios (each rep's legs divide "
                "by its own plain leg — host throughput drifts between "
                "reps, and unpaired mins fold that drift into the "
                "comparison).  The kill leg is reported separately "
                "because its child pays a fresh python + jax import + "
                "compile-cache load — process-replacement cost, not "
                "engine overhead" % reps,
    }


def bench_serving(jax, jnp, jr):
    """Serving front-end config (ISSUE 10 acceptance): what does
    CONTINUOUS BATCHING buy, and does the service SURVIVE overload?

    Three legs:

    1. ``sequential`` — the same-work baseline: every request run ALONE
       (B=1 through the coalesced entry at equal padded capacity), one
       after another.  Also the parity reference: leg 2's results must
       be bit-identical per request (asserted + pinned as
       ``bit_exact_vs_alone``).
    2. ``serving`` — N concurrent synthetic clients submit the SAME
       requests against a live :class:`AgreementService`; per-request
       submit→result latencies give the pinned p50/p99.
    3. ``storm`` — the committed ``examples/faults/deadline_storm.json``
       client plan shapes a saturating fleet (late arrivals, abandoned
       tickets, a near-zero-deadline storm) against a deliberately tiny
       queue while an engine-phase stall plan slows every cohort: the
       service must shed/reject EXPLICITLY (``Overloaded`` /
       ``DeadlineExceeded``), never deadlock or grow the queue past its
       bound, and still serve a probe request afterwards — the
       acceptance booleans ``overload_survived_ok`` / ``queue_bounded``
       / ``shed_rate_bounded``.
    """
    import threading

    import numpy as np

    from ba_tpu.core.state import SimState
    from ba_tpu.core.types import COMMAND_DTYPE, command_from_name
    from ba_tpu.obs.registry import MetricsRegistry
    from ba_tpu.parallel.pipeline import coalesced_sweep, fresh_copy
    from ba_tpu.runtime import chaos as chaos_mod
    from ba_tpu.runtime.serve import (
        AgreementRequest,
        AgreementService,
        DeadlineExceeded,
        Overloaded,
        RequestFailed,
        ServeConfig,
    )

    clients = int(os.environ.get("BA_TPU_BENCH_SERVE_CLIENTS", 8))
    per_client = int(os.environ.get("BA_TPU_BENCH_SERVE_REQS", 4))
    rounds = int(os.environ.get("BA_TPU_BENCH_SERVE_ROUNDS", 32))
    max_batch = int(os.environ.get("BA_TPU_BENCH_SERVE_BATCH", 8))
    cap = 4

    def request(c, j):
        i = c * per_client + j
        return AgreementRequest(
            kind="run-rounds",
            order=("attack", "retreat")[i % 2],
            n=4,
            faulty=((2,), (), (1, 3))[i % 3],
            seed=1000 + i,
            rounds=rounds,
        )

    requests = [
        request(c, j) for c in range(clients) for j in range(per_client)
    ]

    def alone_state(req):
        faulty = np.zeros((1, cap), np.bool_)
        alive = np.zeros((1, cap), np.bool_)
        alive[0, : req.n] = True
        for i in req.faulty:
            faulty[0, i] = True
        return fresh_copy(
            SimState(
                order=jnp.full(
                    (1,), command_from_name(req.order), COMMAND_DTYPE
                ),
                leader=jnp.zeros((1,), jnp.int32),
                faulty=jnp.asarray(faulty),
                alive=jnp.asarray(alive),
                ids=jnp.asarray(
                    np.arange(1, cap + 1, dtype=np.int32)[None, :]
                ),
            )
        )

    def alone(req):
        return coalesced_sweep(
            [jr.key(req.seed)], alone_state(req), rounds,
            rounds_per_dispatch=8,
        )

    # Warm every specialization off the clock (B=1 baseline; the serve
    # leg's batched shapes warm inside its own first window, which the
    # p99 deliberately includes — a real service pays its compiles).
    alone(requests[0])

    t0 = time.perf_counter()
    refs = [alone(req) for req in requests]
    t_seq = time.perf_counter() - t0
    ref_by_seed = {
        req.seed: (
            [int(v) for v in ref["decisions"][:, 0]],
            {
                name: int(v)
                for name, v in zip(ref["counter_names"], ref["counters"][0])
            },
        )
        for req, ref in zip(requests, refs)
    }

    # Leg 2: N concurrent clients against a live service.
    svc = AgreementService(
        ServeConfig(
            max_batch=max_batch, max_queue=4 * max_batch,
            coalesce_window_s=0.01, rounds_per_dispatch=8,
        ),
        registry=MetricsRegistry(),
    )
    svc.start()
    latencies = [0.0] * len(requests)
    mismatches = []
    errors = []

    def client(c):
        for j in range(per_client):
            req = request(c, j)
            t0 = time.perf_counter()
            try:
                out = svc.submit(req, deadline_s=None).result(timeout=600)
            except Exception as e:  # terminal failures count as errors
                errors.append(f"{type(e).__name__}: {e}")
                return
            latencies[c * per_client + j] = time.perf_counter() - t0
            want = ref_by_seed[req.seed]
            if (out["decisions"], out["counters"]) != want:
                mismatches.append(req.seed)

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=client, args=(c,)) for c in range(clients)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=900)
    t_serve = time.perf_counter() - t0
    stats = svc.stats()
    svc.stop()
    assert not errors, errors
    assert not mismatches, f"serving results diverged: seeds {mismatches}"
    lat = sorted(latencies)
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    seq_per_req = t_seq / len(requests)
    # Generous CPU budget: a batched request may wait a full window +
    # one whole cohort's wall (max_batch slots), plus first-window
    # compile amortization; 10x headroom on top keeps the pin about
    # pathology (a stuck dispatcher), not host noise.
    p99_budget = max(2.0, 10 * (seq_per_req * max_batch + 0.01))

    # Leg 3: the deadline-storm drill — committed client plan + a tiny
    # queue + an engine stall slowing every cohort.
    storm_plan = chaos_mod.load("examples/faults/deadline_storm.json")
    client_inj = chaos_mod.ChaosInjector(storm_plan)
    # One stall entry PER DISPATCH WINDOW (faults match lo <= round <
    # hi, so a single round-0 entry would slow only each cohort's
    # first dispatch): every dispatch of every cohort sleeps 50 ms.
    stall_plan = chaos_mod.from_dict(
        {
            "name": "storm-stall",
            "faults": [
                {"round": r, "kind": "stall", "phase": "dispatch",
                 "seconds": 0.05, "times": -1}
                for r in range(0, rounds, 8)
            ],
        }
    )
    storm_queue = max(2, max_batch // 2)
    svc2 = AgreementService(
        ServeConfig(
            max_batch=max_batch, max_queue=storm_queue,
            coalesce_window_s=0.01, rounds_per_dispatch=8,
        ),
        fault_plan=stall_plan,
        registry=MetricsRegistry(),
    )
    svc2.start()
    storm_counts = {"ok": 0, "rejected": 0, "expired": 0, "failed": 0}
    storm_lock = threading.Lock()
    storming = threading.Event()
    ordinals = iter(range(10**9))

    def storm_client(c):
        for j in range(per_client):
            req = request(c, j)
            ordinal = next(ordinals)
            abandon = False
            for f in client_inj.client_faults(ordinal):
                if f.kind == "slow_client":
                    time.sleep(f.seconds)
                elif f.kind == "abandon":
                    abandon = True
                elif f.kind == "deadline_storm":
                    storming.set()
            deadline = 0.001 if storming.is_set() else 5.0
            try:
                ticket = svc2.submit(req, deadline_s=deadline)
            except Overloaded:
                with storm_lock:
                    storm_counts["rejected"] += 1
                continue
            if abandon:
                continue  # never read the ticket; the service still must
            try:
                ticket.result(timeout=600)
                with storm_lock:
                    storm_counts["ok"] += 1
            except DeadlineExceeded:
                with storm_lock:
                    storm_counts["expired"] += 1
            except RequestFailed:
                with storm_lock:
                    storm_counts["failed"] += 1

    storm_threads = [
        threading.Thread(target=storm_client, args=(c,))
        for c in range(2 * clients)
    ]
    t0 = time.perf_counter()
    for th in storm_threads:
        th.start()
    for th in storm_threads:
        th.join(timeout=900)
    t_storm = time.perf_counter() - t0
    hung = sum(1 for th in storm_threads if th.is_alive())
    storm_stats = svc2.stats()
    # Survival probe: the service must still serve AFTER the storm —
    # which includes DECAYING its shed tier (the dispatcher re-evaluates
    # on idle ticks, up to ~50 ms away, so the probe retries through any
    # stale tier-2/3 window instead of racing it; never recovering
    # within the bound IS the overload-survival failure).
    probe_ticket = None
    for _ in range(200):
        try:
            probe_ticket = svc2.submit(request(0, 0), deadline_s=None)
            break
        except Overloaded:
            time.sleep(0.05)
    assert probe_ticket is not None, (
        "service never decayed its shed tier after the storm"
    )
    probe = probe_ticket.result(timeout=600)
    svc2.stop()
    probe_ok = probe["decisions"] == ref_by_seed[request(0, 0).seed][0]
    shed_total = storm_counts["rejected"] + storm_counts["expired"]

    return {
        "rounds_per_sec": round(len(requests) * rounds / t_serve, 1),
        "clients": clients,
        "requests": len(requests),
        "rounds": rounds,
        "n_max": cap,
        "max_batch": max_batch,
        "sequential_elapsed_s": round(t_seq, 4),
        "serving_elapsed_s": round(t_serve, 4),
        "serving_speedup_vs_sequential": round(t_seq / t_serve, 3),
        "p50_latency_s": round(p50, 4),
        "p99_latency_s": round(p99, 4),
        "p99_budget_s": round(p99_budget, 4),
        "p99_within_budget": p99 <= p99_budget,
        "bit_exact_vs_alone": not mismatches,
        "batches": stats["batches"],
        "storm_elapsed_s": round(t_storm, 4),
        "storm_requests": 2 * clients * per_client,
        "storm_ok": storm_counts["ok"],
        "storm_rejected": storm_counts["rejected"],
        "storm_expired": storm_counts["expired"],
        "storm_failed": storm_counts["failed"],
        "storm_injected_client_faults": len(client_inj.fired),
        "storm_queue_limit": storm_queue,
        "storm_queue_depth_final": storm_stats["queue_depth"],
        "overload_survived_ok": hung == 0 and probe_ok,
        "queue_bounded": storm_stats["queue_depth"] <= storm_queue,
        "shed_rate_bounded": shed_total > 0 and storm_counts["ok"] > 0,
        "bound": "leg 2 is bit-identical to leg 1 per request "
                 "(asserted); the serving delta is the coalescing "
                 "window + shared-batch wall; the storm leg pins "
                 "explicit shedding (bounded queue, Overloaded/"
                 "DeadlineExceeded) with zero hung clients and a "
                 "served post-storm probe",
        "note": "p50/p99 include the serve leg's first-window compile "
                "amortization (a real service pays its compiles); the "
                "storm leg's engine stall (50 ms/dispatch, unlimited) "
                "is what makes a CPU-fast cohort saturate the tiny "
                "queue deterministically enough to pin shedding",
    }


def bench_serving_warm(jax, jnp, jr):
    """Warm-serving config (ISSUE 11 acceptance): does the AOT warmup
    pass actually kill the cold-start tail?

    Three legs over identical request fleets:

    1. ``alone`` — every request run by itself (B=1 coalesced entry) —
       the bit-exactness reference for both serving legs.
    2. ``cold`` — a fresh service WITHOUT the executable cache (the
       ISSUE 10 configuration): first-window jit compiles land on
       request latency, the committed r11 pathology, re-measured here so
       cold and warm share one process/host for the contrast
       (``obs.reset_first_calls()`` between legs keeps the request-path
       compile classification honest per leg).
    3. ``warm`` — open → background AOT warmup (``runtime/warmup.py``)
       → warm barrier → the same traffic.  The acceptance booleans:
       ``warm_no_request_path_compiles`` (the service's request-path
       compile counter stayed 0 — every dispatch hit a precompiled
       executable) and ``p99_within_5x_p50`` (the tail is batching
       jitter, not compilation), plus per-request bit-exactness vs BOTH
       the alone refs and the cold leg.
    """
    import tempfile
    import threading

    import numpy as np

    from ba_tpu import obs
    from ba_tpu.core.state import SimState
    from ba_tpu.core.types import COMMAND_DTYPE, command_from_name
    from ba_tpu.obs.registry import MetricsRegistry
    from ba_tpu.parallel.pipeline import coalesced_sweep, fresh_copy
    from ba_tpu.runtime.serve import (
        AgreementRequest,
        AgreementService,
        ServeConfig,
    )

    clients = int(os.environ.get("BA_TPU_BENCH_SERVE_CLIENTS", 8))
    per_client = int(os.environ.get("BA_TPU_BENCH_SERVE_REQS", 4))
    rounds = int(os.environ.get("BA_TPU_BENCH_SERVE_ROUNDS", 32))
    max_batch = int(os.environ.get("BA_TPU_BENCH_SERVE_BATCH", 8))
    cap = 4

    def request(c, j):
        i = c * per_client + j
        return AgreementRequest(
            kind="run-rounds",
            order=("attack", "retreat")[i % 2],
            n=4,
            faulty=((2,), (), (1, 3))[i % 3],
            seed=2000 + i,
            rounds=rounds,
        )

    requests = [
        request(c, j) for c in range(clients) for j in range(per_client)
    ]

    def alone(req):
        faulty = np.zeros((1, cap), np.bool_)
        alive = np.zeros((1, cap), np.bool_)
        alive[0, : req.n] = True
        for i in req.faulty:
            faulty[0, i] = True
        state = fresh_copy(
            SimState(
                order=jnp.full(
                    (1,), command_from_name(req.order), COMMAND_DTYPE
                ),
                leader=jnp.zeros((1,), jnp.int32),
                faulty=jnp.asarray(faulty),
                alive=jnp.asarray(alive),
                ids=jnp.asarray(
                    np.arange(1, cap + 1, dtype=np.int32)[None, :]
                ),
            )
        )
        return coalesced_sweep(
            [jr.key(req.seed)], state, rounds, rounds_per_dispatch=8
        )

    alone(requests[0])  # B=1 specialization warms off the clock
    refs = {}
    for req in requests:
        out = alone(req)
        refs[req.seed] = (
            [int(v) for v in out["decisions"][:, 0]],
            {
                name: int(v)
                for name, v in zip(out["counter_names"], out["counters"][0])
            },
        )

    def drive(svc):
        """The shared client fleet: submit all requests concurrently,
        return (latencies, per-seed results, errors, wall)."""
        latencies = [0.0] * len(requests)
        results = {}
        errors = []
        lock = threading.Lock()

        def client(c):
            for j in range(per_client):
                req = request(c, j)
                t0 = time.perf_counter()
                try:
                    out = svc.submit(req, deadline_s=None).result(
                        timeout=600
                    )
                except Exception as e:
                    errors.append(f"{type(e).__name__}: {e}")
                    return
                wall = time.perf_counter() - t0
                with lock:
                    latencies[c * per_client + j] = wall
                    results[req.seed] = (
                        out["decisions"], out["counters"]
                    )

        threads = [
            threading.Thread(target=client, args=(c,))
            for c in range(clients)
        ]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=900)
        return latencies, results, errors, time.perf_counter() - t0

    def pcts(latencies):
        lat = sorted(latencies)
        return (
            lat[len(lat) // 2],
            lat[min(len(lat) - 1, int(len(lat) * 0.99))],
        )

    # Leg 2: COLD — no executable cache, first-window compiles on the
    # request path (the r11 configuration, re-measured in this process).
    # The env knob is neutralized for the leg's duration: a user-level
    # BA_TPU_AOT_CACHE pointing at a populated dir would silently warm
    # this leg and the cold/warm contrast would measure nothing.
    obs.reset_first_calls()
    aot_env = os.environ.pop("BA_TPU_AOT_CACHE", None)
    try:
        svc_cold = AgreementService(
            ServeConfig(
                max_batch=max_batch, max_queue=4 * max_batch,
                coalesce_window_s=0.01, rounds_per_dispatch=8,
            ),
            registry=MetricsRegistry(),
        )
        svc_cold.start()
        cold_lat, cold_res, cold_err, t_cold = drive(svc_cold)
        cold_stats = svc_cold.stats()
        svc_cold.stop()
    finally:
        if aot_env is not None:
            os.environ["BA_TPU_AOT_CACHE"] = aot_env
    assert not cold_err, cold_err
    cold_mismatch = [
        seed for seed, (dec, ctr) in cold_res.items()
        if (dec, ctr) != refs[seed]
    ]
    assert not cold_mismatch, f"cold serving diverged: {cold_mismatch}"
    cold_p50, cold_p99 = pcts(cold_lat)

    # Leg 3: WARM — open → background AOT warmup → warm barrier →
    # traffic.  The cache persists into a temp dir (never user cache
    # state); reset_first_calls keeps the per-leg compile classification
    # honest (without it, leg 2's compiles would mask leg 3's counter).
    obs.reset_first_calls()
    with tempfile.TemporaryDirectory() as aot_dir:
        svc_warm = AgreementService(
            ServeConfig(
                max_batch=max_batch, max_queue=4 * max_batch,
                coalesce_window_s=0.01, rounds_per_dispatch=8,
                warm=True, warm_rounds=rounds, aot_cache=aot_dir,
                # This leg's fleet is run-rounds only; scenario
                # specializations would double warmup wall for traffic
                # the leg never sends (the service default warms both).
                warm_scenarios=False,
            ),
            registry=MetricsRegistry(),
        )
        t0 = time.perf_counter()
        svc_warm.open()
        warm_ok = svc_warm.warm_barrier(timeout=600)
        t_warmup = time.perf_counter() - t0
        assert warm_ok, "warm barrier timed out"
        warmup_prog = svc_warm._warmup.progress()
        svc_warm.start()
        warm_lat, warm_res, warm_err, t_warm = drive(svc_warm)
        warm_stats = svc_warm.stats()
        svc_warm.stop()
    assert not warm_err, warm_err
    warm_vs_ref = [
        seed for seed, (dec, ctr) in warm_res.items()
        if (dec, ctr) != refs[seed]
    ]
    assert not warm_vs_ref, f"warm serving diverged from alone: {warm_vs_ref}"
    # Per-request bit-exactness vs the COLD leg (the ISSUE 11 pin: the
    # executable cache is a latency optimization, never a semantic one).
    warm_vs_cold = [
        seed for seed in warm_res if warm_res[seed] != cold_res[seed]
    ]
    assert not warm_vs_cold, f"warm != cold per request: {warm_vs_cold}"
    # The acceptance boolean is also an ASSERT: a lattice/axes drift
    # that reintroduces request-path compiles must fail the bench, not
    # quietly flip a boolean in the artifact.
    assert warm_stats["compiles_on_request_path"] == 0, (
        f"warm service compiled on the request path "
        f"({warm_stats['compiles_on_request_path']}x after the barrier)"
    )
    warm_p50, warm_p99 = pcts(warm_lat)

    return {
        "rounds_per_sec": round(len(requests) * rounds / t_warm, 1),
        "clients": clients,
        "requests": len(requests),
        "rounds": rounds,
        "n_max": cap,
        "max_batch": max_batch,
        "cold_elapsed_s": round(t_cold, 4),
        "cold_p50_latency_s": round(cold_p50, 4),
        "cold_p99_latency_s": round(cold_p99, 4),
        "cold_p99_over_p50": round(cold_p99 / cold_p50, 1),
        "cold_request_path_compiles": cold_stats[
            "compiles_on_request_path"
        ],
        "warmup_wall_s": round(t_warmup, 4),
        "warmup_signatures": warmup_prog["planned"],
        "warmup_compiled": warmup_prog["compiled"],
        "warmup_errors": warmup_prog["errors"],
        "warm_elapsed_s": round(t_warm, 4),
        "warm_p50_latency_s": round(warm_p50, 4),
        "warm_p99_latency_s": round(warm_p99, 4),
        "warm_p99_over_p50": round(warm_p99 / warm_p50, 1),
        "warm_request_path_compiles": warm_stats[
            "compiles_on_request_path"
        ],
        "warm_no_request_path_compiles": (
            warm_stats["compiles_on_request_path"] == 0
        ),
        "p99_within_5x_p50": warm_p99 <= 5 * warm_p50,
        "bit_exact_vs_cold": not warm_vs_cold and not warm_vs_ref,
        "p99_improvement_vs_cold": round(cold_p99 / warm_p99, 1),
        "bound": "all three legs are bit-identical per request "
                 "(asserted); the cold leg re-measures the r11 "
                 "first-window-compile tail in this process, the warm "
                 "leg serves the same traffic entirely from "
                 "AOT-precompiled executables (request-path compile "
                 "counter asserted 0 after the warm barrier)",
        "note": "warmup wall is the background pass start->barrier "
                "(off the request path by construction); cold p99 "
                "includes real jit compiles of the batched "
                "specializations (first time in this process), warm "
                "p99 is batching jitter only — the ISSUE 11 target is "
                "warm p99 <= 5x warm p50 vs the cold ~60x",
    }


def bench_serving_slo(jax, jnp, jr):
    """SLO-engine config (ISSUE 17 acceptance): does the streaming SLO
    engine attribute every request's latency, fire/clear its burn alert
    through a burst, and stay bit-exact + compile-free while doing it?

    One warm service with a LIVE SLO policy serves four phases into a
    captured metrics stream:

    1. ``serve`` — a mixed-tenant client fleet (tenant per client) over
       one warmed cohort; per-request bit-exactness vs the B=1 alone
       refs (``bit_exact_vs_alone``), zero request-path compiles after
       the warm barrier (``no_request_path_compiles``).
    2. ``quiet`` — an idle gap longer than the slow burn window, so the
       healthy traffic ages out of every ring.
    3. ``burst`` — the committed ``examples/faults/deadline_storm.json``
       CLIENT plan shapes a storm (slow clients, an abandon, then
       near-zero deadlines): expired/rejected requests burn error
       budget until BOTH burn windows exceed threshold — the alert must
       FIRE (``slo_alert`` state=fire) and an ``autoscale_signal`` must
       recommend scaling up.
    4. ``recover`` — the burst drains, the fast window empties, the
       alert must CLEAR, and a probe request serves normally.

    The acceptance booleans are recomputed from the CAPTURED JSONL (the
    same stream ``scripts/obs_report.py --slo`` renders), not from
    in-process state: ``attribution_sums_ok`` (every ok request's five
    phases telescope to its wall within ATTRIB_TOL_S),
    ``burn_alert_fired_and_cleared``, ``tenant_accounting_ok`` (final
    report's per-tenant ok tallies match the fleet), plus the two
    serving pins above — all asserted, not just recorded.
    """
    import tempfile
    import threading

    import numpy as np

    from ba_tpu import obs
    from ba_tpu.core.state import SimState
    from ba_tpu.core.types import COMMAND_DTYPE, command_from_name
    from ba_tpu.obs import slo as slo_mod
    from ba_tpu.obs.registry import MetricsRegistry
    from ba_tpu.parallel.pipeline import coalesced_sweep, fresh_copy
    from ba_tpu.runtime import chaos as chaos_mod
    from ba_tpu.runtime.serve import (
        AgreementRequest,
        AgreementService,
        Overloaded,
        ServeConfig,
    )
    from ba_tpu.utils import metrics as metrics_mod

    clients = int(os.environ.get("BA_TPU_BENCH_SERVE_CLIENTS", 4))
    per_client = int(os.environ.get("BA_TPU_BENCH_SERVE_REQS", 3))
    rounds = int(os.environ.get("BA_TPU_BENCH_SERVE_ROUNDS", 16))
    max_batch = int(os.environ.get("BA_TPU_BENCH_SERVE_BATCH", 4))
    burst_n = int(os.environ.get("BA_TPU_BENCH_SLO_BURST", 120))
    cap = 4
    fast_w, slow_w = 1.0, 3.0

    def request(c, j, tenant=None):
        i = c * per_client + j
        return AgreementRequest(
            kind="run-rounds",
            order=("attack", "retreat")[i % 2],
            n=4,
            faulty=((2,), (), (1, 3))[i % 3],
            seed=3000 + i,
            rounds=rounds,
            tenant=tenant or f"tenant-{c}",
        )

    requests = [
        request(c, j) for c in range(clients) for j in range(per_client)
    ]

    def alone(req):
        faulty = np.zeros((1, cap), np.bool_)
        alive = np.zeros((1, cap), np.bool_)
        alive[0, : req.n] = True
        for i in req.faulty:
            faulty[0, i] = True
        state = fresh_copy(
            SimState(
                order=jnp.full(
                    (1,), command_from_name(req.order), COMMAND_DTYPE
                ),
                leader=jnp.zeros((1,), jnp.int32),
                faulty=jnp.asarray(faulty),
                alive=jnp.asarray(alive),
                ids=jnp.asarray(
                    np.arange(1, cap + 1, dtype=np.int32)[None, :]
                ),
            )
        )
        return coalesced_sweep(
            [jr.key(req.seed)], state, rounds, rounds_per_dispatch=8
        )

    alone(requests[0])  # B=1 specialization warms off the clock
    refs = {}
    for req in requests:
        out = alone(req)
        refs[req.seed] = (
            [int(v) for v in out["decisions"][:, 0]],
            {
                name: int(v)
                for name, v in zip(out["counter_names"], out["counters"][0])
            },
        )

    policy = slo_mod.SLOPolicy(
        objectives=(
            slo_mod.SLOObjective(
                name="serve-wall",
                latency_s=30.0,  # ok == good; expired/rejected burn
                target=0.5,  # burn = 2 * bad_frac: all-bad burns at 2.0
                window_s=60.0,
                fast_window_s=fast_w,
                slow_window_s=slow_w,
                burn_threshold=1.5,
            ),
        ),
        report_every_s=0.05,
    )

    fd, capture = tempfile.mkstemp(suffix=".jsonl")
    os.close(fd)
    prev_target = metrics_mod.default_sink().target
    obs.reset_first_calls()
    metrics_mod.configure(capture)
    try:
        with tempfile.TemporaryDirectory() as aot_dir:
            svc = AgreementService(
                ServeConfig(
                    max_batch=max_batch, max_queue=4 * max_batch,
                    coalesce_window_s=0.01, rounds_per_dispatch=8,
                    warm=True, warm_rounds=rounds, aot_cache=aot_dir,
                    warm_scenarios=False, slo=policy,
                ),
                registry=MetricsRegistry(),
            )
            t0 = time.perf_counter()
            svc.open()
            assert svc.warm_barrier(timeout=600), "warm barrier timed out"
            t_warmup = time.perf_counter() - t0
            svc.start()

            # Phase 1: the mixed-tenant fleet.
            latencies = [0.0] * len(requests)
            results = {}
            errors = []
            lock = threading.Lock()

            def client(c):
                for j in range(per_client):
                    req = request(c, j)
                    t1 = time.perf_counter()
                    try:
                        out = svc.submit(req, deadline_s=None).result(
                            timeout=600
                        )
                    except Exception as e:
                        errors.append(f"{type(e).__name__}: {e}")
                        return
                    wall = time.perf_counter() - t1
                    with lock:
                        latencies[c * per_client + j] = wall
                        results[req.seed] = (
                            out["decisions"], out["counters"]
                        )

            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(clients)
            ]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=900)
            t_serve = time.perf_counter() - t0
            assert not errors, errors
            mismatches = [
                seed for seed, got in results.items() if got != refs[seed]
            ]
            assert not mismatches, f"serving diverged: seeds {mismatches}"

            # Phase 2: quiet gap — healthy traffic ages out of the slow
            # ring (reports keep flowing on the dispatcher's idle ticks).
            time.sleep(slow_w + 0.3)

            # Phase 3: the storm — committed client plan shapes it; once
            # deadline_storm fires, the client floods back-to-back with
            # near-zero budgets: the queue fills (rejects burn), queued
            # tickets expire at pop (expiries burn), and burn climbs
            # past threshold in BOTH windows.  Burst traffic carries a
            # dedicated tenant so the per-tenant accounting pin on the
            # fleet tenants below stays exact regardless of how the
            # served/expired race splits inside the storm.
            storm_plan = chaos_mod.load("examples/faults/deadline_storm.json")
            injector = chaos_mod.ChaosInjector(storm_plan)
            storming = threading.Event()
            burst_counts = {"submitted": 0, "rejected": 0}
            t0 = time.perf_counter()
            for ordinal in range(burst_n):
                for f in injector.client_faults(ordinal):
                    if f.kind == "slow_client":
                        time.sleep(f.seconds)
                    elif f.kind == "deadline_storm":
                        storming.set()
                deadline = 0.002 if storming.is_set() else 5.0
                req = request(
                    ordinal % clients, ordinal % per_client,
                    tenant="tenant-burst",
                )
                try:
                    svc.submit(req, deadline_s=deadline)
                    burst_counts["submitted"] += 1
                except Overloaded:
                    burst_counts["rejected"] += 1
                if not storming.is_set():
                    time.sleep(0.005)
            # Drain: every burst ticket popped (expired) or served.
            for _ in range(600):
                if svc.stats()["queue_depth"] == 0:
                    break
                time.sleep(0.05)
            t_burst = time.perf_counter() - t0

            # Phase 4: recovery — the fast window empties, the alert
            # clears, and a probe request serves normally.
            time.sleep(fast_w + 0.4)
            probe_req = request(0, 0)
            probe = None
            for _ in range(200):
                try:
                    probe = svc.submit(probe_req, deadline_s=None).result(
                        timeout=600
                    )
                    break
                except Overloaded:
                    time.sleep(0.05)
            assert probe is not None, "service never recovered post-burst"
            probe_ok = probe["decisions"] == refs[probe_req.seed][0]
            stats = svc.stats()
            svc.stop()
    finally:
        metrics_mod.configure(prev_target)

    # Recompute the acceptance booleans from the CAPTURED stream.
    recs = []
    with open(capture, encoding="utf-8") as f:
        for line in f:
            recs.append(json.loads(line))
    ok_reqs = [
        r for r in recs
        if r.get("event") == "request" and r.get("status") == "ok"
    ]
    expired = sum(
        1
        for r in recs
        if r.get("event") == "request" and r.get("status") == "expired"
    )
    attrib_bad = []
    for r in ok_reqs:
        phases = [r.get(k) for k in slo_mod.PHASES]
        if not all(isinstance(p, (int, float)) for p in phases) or abs(
            sum(phases) - r["wall_s"]
        ) > slo_mod.ATTRIB_TOL_S:
            attrib_bad.append(r["id"])
    attribution_sums_ok = not attrib_bad
    assert attribution_sums_ok, f"attribution broke: request ids {attrib_bad}"

    alerts = [r for r in recs if r.get("event") == "slo_alert"]
    states = [a["state"] for a in alerts]
    fired_and_cleared = (
        "fire" in states
        and "clear" in states
        and states.index("fire") < len(states) - 1 - states[::-1].index(
            "clear"
        )
    )
    assert fired_and_cleared, f"alert lifecycle broke: {states}"

    signals = [r for r in recs if r.get("event") == "autoscale_signal"]
    scale_up = [s for s in signals if s["recommended"] > s["replicas"]]
    autoscale_scale_up_ok = bool(scale_up)
    assert autoscale_scale_up_ok, "no scale-up autoscale_signal in the burst"

    reports = [r for r in recs if r.get("event") == "slo_report"]
    assert reports, "no slo_report records captured"
    # Fleet tenants must tally EXACTLY (fleet + probe); the storm rode
    # a dedicated tenant, so its racy served/expired split lands in its
    # own group and must show burned budget there.
    want_ok = {}
    for req in requests:
        want_ok[req.tenant] = want_ok.get(req.tenant, 0) + 1
    want_ok["tenant-0"] += 1  # the recovery probe
    got_ok = {
        g["tenant"]: g["counts"].get("ok", 0)
        for g in reports[-1]["groups"]
    }
    burst_burned = sum(
        g["counts"].get("expired", 0) + g["counts"].get("rejected", 0)
        for g in reports[-1]["groups"]
        if g["tenant"] == "tenant-burst"
    )
    tenant_accounting_ok = (
        all(got_ok.get(tenant, 0) == n for tenant, n in want_ok.items())
        and burst_burned > 0
    )
    assert tenant_accounting_ok, (
        f"want {want_ok}, got {got_ok}, burst burned {burst_burned}"
    )
    assert stats["compiles_on_request_path"] == 0, (
        f"SLO service compiled on the request path "
        f"({stats['compiles_on_request_path']}x after the barrier)"
    )
    os.unlink(capture)  # asserts passed — a failing run keeps its stream

    lat = sorted(latencies)
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    peak_burn = max(
        (o["burn"] for r in reports for o in r["objectives"]
         if o["burn"] is not None),
        default=None,
    )
    return {
        "rounds_per_sec": round(len(requests) * rounds / t_serve, 1),
        "clients": clients,
        "requests": len(requests),
        "tenants": clients,
        "rounds": rounds,
        "n_max": cap,
        "max_batch": max_batch,
        "warmup_wall_s": round(t_warmup, 4),
        "serve_elapsed_s": round(t_serve, 4),
        "p50_latency_s": round(p50, 4),
        "p99_latency_s": round(p99, 4),
        "burst_submitted": burst_counts["submitted"],
        "burst_rejected": burst_counts["rejected"],
        "burst_expired": expired,
        "burst_elapsed_s": round(t_burst, 4),
        "slo_reports": len(reports),
        "slo_alerts": states,
        "peak_gate_burn": peak_burn,
        "attribution_checked": sum(
            g["attribution_checked"] for g in reports[-1]["groups"]
        ),
        "attribution_sums_ok": attribution_sums_ok,
        "burn_alert_fired_and_cleared": fired_and_cleared,
        "autoscale_scale_up_ok": autoscale_scale_up_ok,
        "tenant_accounting_ok": tenant_accounting_ok,
        "bit_exact_vs_alone": not mismatches and probe_ok,
        "no_request_path_compiles": (
            stats["compiles_on_request_path"] == 0
        ),
        "bound": "the serve phase is bit-identical to the B=1 alone "
                 "refs per request (asserted); every acceptance "
                 "boolean is recomputed from the captured JSONL stream "
                 "and asserted — a regression fails the bench, it "
                 "never just flips a committed boolean",
        "note": "burn windows are deliberately tiny (fast 1 s / slow "
                "3 s, target 0.5, threshold 1.5) so the committed "
                "deadline-storm client plan drives a full "
                "fire->clear alert lifecycle in seconds; phase "
                "attribution runs through the same warm executables "
                "the no-compile pin covers",
    }


def bench_fleet_trace(jax, jnp, jr):
    """Fleet-tracing config (ISSUE 19 acceptance): does one served
    request on a POOLED SIGNED cohort reconstruct to a single
    cross-process span tree?

    A warm service in sink-DIRECTORY mode (``BA_TPU_METRICS=dir/``,
    one shard per process) serves a mixed-tenant signed fleet with the
    sign pool live, then every acceptance boolean is recomputed from
    the CAPTURED SHARDS — the same files ``python -m ba_tpu.obs.fleet``
    merges — and asserted, not just recorded:

    - ``all_spans_parented`` — every request's assembled span tree has
      ZERO unparented spans (the batch fan-in grafts, the pool workers'
      ``pool_task`` spans parent under the piped traceparent, the
      request root is the tree root).
    - ``critical_path_within_tol`` — each request's five attributed
      phases telescope to its wall within ``ATTRIB_TOL_S`` (the PR 17
      invariant, surviving reassembly from shards).
    - ``merge_deterministic`` — two independent merges of the same
      shard set are byte-identical (same canonical digest).
    - ``cross_process`` — every request tree spans >= 2 processes (the
      dispatcher's shard plus at least one pool worker's).
    - ``no_request_path_compiles`` — zero compiles after the warm
      barrier, with the whole tracing plane live (the zero-added-sync
      contract priced: context rides existing emits).
    """
    import shutil
    import tempfile
    import threading

    from ba_tpu import obs
    from ba_tpu.crypto import pool as pool_mod
    from ba_tpu.obs import fleet as fleet_mod
    from ba_tpu.obs.registry import MetricsRegistry
    from ba_tpu.runtime.serve import (
        AgreementRequest,
        AgreementService,
        ServeConfig,
    )
    from ba_tpu.utils import metrics as metrics_mod

    clients = int(os.environ.get("BA_TPU_BENCH_FLEET_CLIENTS", 3))
    per_client = int(os.environ.get("BA_TPU_BENCH_FLEET_REQS", 2))
    rounds = int(os.environ.get("BA_TPU_BENCH_FLEET_ROUNDS", 12))
    max_batch = 4

    def request(c, j):
        i = c * per_client + j
        return AgreementRequest(
            kind="run-rounds",
            order=("attack", "retreat")[i % 2],
            n=4,
            faulty=((2,), (), (1, 3))[i % 3],
            seed=7000 + i,
            rounds=rounds,
            m=1,
            signed=True,
            tenant=f"tenant-{c}",
        )

    sink_dir = tempfile.mkdtemp(prefix="ba_fleet_trace_") + os.sep
    prev_target = metrics_mod.default_sink().target
    prev_env = {
        k: os.environ.get(k)
        for k in ("BA_TPU_METRICS", "BA_TPU_SIGN_POOL",
                  "BA_TPU_SIGN_CACHE")
    }
    os.environ["BA_TPU_METRICS"] = sink_dir
    os.environ["BA_TPU_SIGN_POOL"] = os.environ.get(
        "BA_TPU_SIGN_POOL"
    ) or "2"
    # Cache OFF for this leg: a primed signature-table cache would
    # satisfy every signed request in-process and the cross-process
    # tree this config exists to pin would have no pool spans to cross.
    os.environ["BA_TPU_SIGN_CACHE"] = "0"
    # Respawn the pool AFTER the sink points at the directory: workers
    # snapshot the live sink target at spawn, and a worker spawned
    # against the previous config's sink would shard elsewhere.
    pool_mod.shutdown_defaults()
    obs.reset_first_calls()
    metrics_mod.configure(sink_dir)
    try:
        with tempfile.TemporaryDirectory() as aot_dir:
            svc = AgreementService(
                ServeConfig(
                    max_batch=max_batch, max_queue=4 * max_batch,
                    coalesce_window_s=0.02, rounds_per_dispatch=4,
                    warm=True, warm_rounds=rounds, aot_cache=aot_dir,
                    warm_scenarios=False,
                ),
                registry=MetricsRegistry(),
            )
            t0 = time.perf_counter()
            svc.open()
            assert svc.warm_barrier(timeout=600), "warm barrier timed out"
            t_warmup = time.perf_counter() - t0
            svc.start()

            errors = []

            def client(c):
                for j in range(per_client):
                    try:
                        svc.submit(
                            request(c, j), deadline_s=None
                        ).result(timeout=600)
                    except Exception as e:
                        errors.append(f"{type(e).__name__}: {e}")
                        return

            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(clients)
            ]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=900)
            t_serve = time.perf_counter() - t0
            assert not errors, errors
            stats = svc.stats()
            svc.stop()  # reaps the pool: worker shards are complete
    finally:
        metrics_mod.configure(prev_target)
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        pool_mod.shutdown_defaults()

    # Recompute every acceptance boolean from the captured shards.
    merged = fleet_mod.merge_shards(sink_dir)
    merge_deterministic = fleet_mod.merge_digest(
        merged
    ) == fleet_mod.merge_digest(fleet_mod.merge_shards(sink_dir))
    assert merge_deterministic, "shard merge is not deterministic"
    rids = fleet_mod.request_ids(merged)
    assert len(rids) == clients * per_client, (
        f"expected {clients * per_client} served requests in the "
        f"stream, found {len(rids)}"
    )
    traces = [
        fleet_mod.assemble_request_trace(merged, request_id=rid)
        for rid in rids
    ]
    all_spans_parented = all(t["unparented"] == [] for t in traces)
    assert all_spans_parented, (
        f"unparented spans: "
        f"{[(t['request_id'], t['unparented']) for t in traces]}"
    )
    critical_path_within_tol = all(t["within_tol"] for t in traces)
    assert critical_path_within_tol, "critical-path attribution broke"
    cross_process = all(len(t["processes"]) >= 2 for t in traces)
    assert cross_process, (
        "a signed request's tree never left the dispatcher process "
        "(no pool-worker span joined it)"
    )
    pool_tasks = sum(1 for r in merged if r.get("event") == "pool_task")
    assert pool_tasks > 0, "no pool_task spans in the worker shards"
    summary = fleet_mod.fleet_summary(merged)
    assert stats["compiles_on_request_path"] == 0, (
        f"request path compiled "
        f"({stats['compiles_on_request_path']}x after the barrier) "
        f"with the tracing plane live"
    )
    shutil.rmtree(sink_dir)  # asserts passed — a failing run keeps it

    n_requests = clients * per_client
    return {
        "rounds_per_sec": round(n_requests * rounds / t_serve, 1),
        "clients": clients,
        "requests": n_requests,
        "tenants": clients,
        "rounds": rounds,
        "max_batch": max_batch,
        "warmup_wall_s": round(t_warmup, 4),
        "serve_elapsed_s": round(t_serve, 4),
        "shards": len(summary["replicas"]),
        "pool_tasks": pool_tasks,
        "spans_per_trace": [t["span_count"] for t in traces],
        "merge_digest": fleet_mod.merge_digest(merged),
        "all_spans_parented": all_spans_parented,
        "critical_path_within_tol": critical_path_within_tol,
        "merge_deterministic": merge_deterministic,
        "cross_process_trees_ok": cross_process,
        "no_request_path_compiles": (
            stats["compiles_on_request_path"] == 0
        ),
        "bound": "every boolean is recomputed from the captured "
                 "shards (the same files `python -m ba_tpu.obs.fleet` "
                 "merges) and asserted — a regression fails the "
                 "bench, it never just flips a committed boolean",
        "note": "sink-directory mode, one shard per process "
                "(dispatcher + sign-pool workers); request trees "
                "assemble across the process boundary via the "
                "traceparent piped with each pool task",
    }


def bench_serving_fleet(jax, jnp, jr):
    """Elastic-fleet config (ISSUE 20 acceptance): does a replicated
    fleet survive losing a member mid-run with zero hung clients and a
    bit-exact migrated campaign?

    Two legs over identical request fleets (one cohort, so every
    request hashes to the same ring home — the worst case for a kill):

    1. ``single`` — a 1-replica fleet behind the router: the baseline
       a replicated deployment must not regress, plus the per-request
       bit-exactness refs (B=1 alone runs).
    2. ``fleet`` — 3 replicas (overlapped warm barriers off a shared
       AOT cache), a live checkpointing campaign on the cohort's hash
       HOME replica, the same client fleet through the router — and
       the home replica is SIGKILLed mid-run.  Queued tickets fail,
       ``RoutedTicket`` re-homes them on survivors inside the caller's
       original timeout; the campaign is abandoned (no handoff header,
       only the fsync'd ledger + periodic checkpoints survive) and
       ``adopt_orphans`` resumes it fingerprint-verified on a survivor.

    The acceptance booleans — all asserted, never just recorded:

    - ``reroute_zero_hung_clients`` — every client got a result
      (bit-exact vs its alone ref) through the kill; no error, no hang.
    - ``migrated_bit_exact`` — the adopted campaign's decisions and
      histograms equal an uninterrupted same-seed run's, with the full
      reassembled history (``history_start == 0``).
    - ``no_request_path_compiles_fleet`` — the per-replica
      ``serve_compile_on_request_path_total`` counters sum to ZERO
      across BOTH legs' rosters (ring entry is warm-gated).
    - ``queue_bounded_all_replicas`` — a health sampler polling every
      replica's lock-free gauges through the storm never saw a queue
      above ``max_queue``.
    """
    import shutil
    import tempfile
    import threading

    import numpy as np

    from ba_tpu import obs
    from ba_tpu.core.state import SimState
    from ba_tpu.core.types import COMMAND_DTYPE, command_from_name
    from ba_tpu.fleet import (
        CampaignSpec,
        FleetConfig,
        FleetRouter,
        ReplicaManager,
    )
    from ba_tpu.parallel import make_sweep_state
    from ba_tpu.parallel.pipeline import coalesced_sweep, fresh_copy
    from ba_tpu.runtime.serve import (
        AgreementRequest,
        ServeConfig,
        cohort_key,
        cohort_label,
    )
    from ba_tpu.runtime.supervisor import (
        SupervisorConfig,
        supervised_sweep,
    )

    clients = int(os.environ.get("BA_TPU_BENCH_FLEET_SERVE_CLIENTS", 6))
    per_client = int(os.environ.get("BA_TPU_BENCH_FLEET_SERVE_REQS", 3))
    rounds = int(os.environ.get("BA_TPU_BENCH_FLEET_SERVE_ROUNDS", 32))
    camp_rounds = int(
        os.environ.get("BA_TPU_BENCH_FLEET_CAMPAIGN_ROUNDS", 4000)
    )
    max_batch = 4
    max_queue = 4 * max_batch
    cap = 4

    def request(c, j):
        i = c * per_client + j
        return AgreementRequest(
            kind="run-rounds",
            order=("attack", "retreat")[i % 2],
            n=4,
            faulty=((2,), (), (1, 3))[i % 3],
            seed=9000 + i,
            rounds=rounds,
        )

    requests = [
        request(c, j) for c in range(clients) for j in range(per_client)
    ]

    def alone(req):
        faulty = np.zeros((1, cap), np.bool_)
        alive = np.zeros((1, cap), np.bool_)
        alive[0, : req.n] = True
        for i in req.faulty:
            faulty[0, i] = True
        state = fresh_copy(
            SimState(
                order=jnp.full(
                    (1,), command_from_name(req.order), COMMAND_DTYPE
                ),
                leader=jnp.zeros((1,), jnp.int32),
                faulty=jnp.asarray(faulty),
                alive=jnp.asarray(alive),
                ids=jnp.asarray(
                    np.arange(1, cap + 1, dtype=np.int32)[None, :]
                ),
            )
        )
        return coalesced_sweep(
            [jr.key(req.seed)], state, rounds, rounds_per_dispatch=8
        )

    alone(requests[0])  # B=1 specialization warms off the clock
    refs = {}
    for req in requests:
        out = alone(req)
        refs[req.seed] = [int(v) for v in out["decisions"][:, 0]]

    def serve_config(aot_dir):
        return ServeConfig(
            max_batch=max_batch, max_queue=max_queue,
            coalesce_window_s=0.02, rounds_per_dispatch=8,
            warm=True, warm_rounds=rounds, aot_cache=aot_dir,
            warm_scenarios=False,
        )

    def drive(router, on_started=None):
        """The shared client fleet through the ROUTER: returns
        (latencies, per-seed decisions, errors, wall)."""
        latencies = [0.0] * len(requests)
        results = {}
        errors = []
        lock = threading.Lock()
        started = threading.Barrier(clients + 1)

        def client(c):
            started.wait(timeout=60)
            for j in range(per_client):
                req = request(c, j)
                t0 = time.perf_counter()
                try:
                    out = router.submit(req, deadline_s=None).result(
                        timeout=600
                    )
                except Exception as e:
                    errors.append(f"{type(e).__name__}: {e}")
                    return
                wall = time.perf_counter() - t0
                with lock:
                    latencies[c * per_client + j] = wall
                    results[req.seed] = [int(v) for v in out["decisions"]]

        threads = [
            threading.Thread(target=client, args=(c,))
            for c in range(clients)
        ]
        for th in threads:
            th.start()
        started.wait(timeout=60)
        t0 = time.perf_counter()
        if on_started is not None:
            on_started()
        for th in threads:
            th.join(timeout=900)
        return latencies, results, errors, time.perf_counter() - t0

    def pcts(latencies):
        lat = sorted(latencies)
        return (
            lat[len(lat) // 2],
            lat[min(len(lat) - 1, int(len(lat) * 0.99))],
        )

    fleet_root = tempfile.mkdtemp(prefix="ba_fleet_serve_")
    with tempfile.TemporaryDirectory() as aot_dir:
        # Leg 1: SINGLE — one replica behind the router, the baseline.
        obs.reset_first_calls()
        mgr1 = ReplicaManager(
            FleetConfig(replicas=1), serve_config=serve_config(aot_dir)
        )
        t0 = time.perf_counter()
        mgr1.start(warm_timeout_s=600)
        t_warm_single = time.perf_counter() - t0
        router1 = FleetRouter(mgr1)
        one_lat, one_res, one_err, t_single = drive(router1)
        assert not one_err, one_err
        single_rpc = sum(
            r.registry.counter(
                "serve_compile_on_request_path_total"
            ).value
            for r in mgr1.all()
        )
        mgr1.stop()
        one_mismatch = [
            seed for seed, dec in one_res.items() if dec != refs[seed]
        ]
        assert not one_mismatch, (
            f"single-replica fleet diverged: {one_mismatch}"
        )
        single_p50, single_p99 = pcts(one_lat)

        # Leg 2: FLEET — 3 replicas, a live campaign on the cohort's
        # hash home, and that home killed mid-run.
        obs.reset_first_calls()
        mgr = ReplicaManager(
            FleetConfig(replicas=3, root=fleet_root),
            serve_config=serve_config(aot_dir),
        )
        t0 = time.perf_counter()
        mgr.start(warm_timeout_s=600)
        t_warm_fleet = time.perf_counter() - t0
        router = FleetRouter(mgr)
        router._sync_ring()
        label = cohort_label(cohort_key(requests[0]))
        victim = router._ring.prefer(label)[0]

        spec = CampaignSpec(
            campaign="bench-fleet", seed=71, state_seed=72, batch=8,
            rounds=camp_rounds, capacity=cap, checkpoint_every=8,
        )
        handle = mgr.get(victim).run_campaign(spec)
        t0 = time.perf_counter()
        while handle.fingerprint is None and not handle.done():
            time.sleep(0.002)
            assert time.perf_counter() - t0 < 120, (
                "campaign produced no fingerprinted checkpoint"
            )

        # Lock-free health sampler: the queue-bound witness.
        peak = {r.name: 0 for r in mgr.all()}
        sampling = threading.Event()
        sampling.set()

        def sample():
            while sampling.is_set():
                for r in mgr.all():
                    depth = r.health()["queue_depth"]
                    if depth > peak[r.name]:
                        peak[r.name] = depth
                time.sleep(0.002)

        sampler = threading.Thread(target=sample, daemon=True)
        sampler.start()

        def kill_home():
            time.sleep(0.05)  # let the first window queue on the home
            mgr.kill(victim)

        lat, res, err, t_fleet = drive(router, on_started=kill_home)
        sampling.clear()
        sampler.join(timeout=10)
        assert not err, f"hung/failed clients through the kill: {err}"
        mismatch = [
            seed for seed, dec in res.items() if dec != refs[seed]
        ]
        assert not mismatch, f"fleet serving diverged: {mismatch}"
        rstats = router.stats()

        # The killed home abandoned its campaign (no handoff header) —
        # adopt the orphan on a survivor and run it to completion.
        assert handle.wait(60) and handle.outcome == "abandoned", (
            f"campaign outcome {handle.outcome!r} — expected the kill "
            f"to land mid-campaign (raise "
            f"BA_TPU_BENCH_FLEET_CAMPAIGN_ROUNDS?)"
        )
        adopted = mgr.adopt_orphans(victim)
        assert len(adopted) == 1, f"adopted {len(adopted)} campaigns"
        assert adopted[0].wait(600) and adopted[0].outcome == "completed", (
            f"adopted campaign ended {adopted[0].outcome!r}: "
            f"{adopted[0].error}"
        )
        fleet_rpc = sum(
            r.registry.counter(
                "serve_compile_on_request_path_total"
            ).value
            for r in mgr.all()
        )
        mgr.stop()

    want = supervised_sweep(
        jr.key(spec.seed),
        make_sweep_state(jr.key(spec.state_seed), spec.batch, cap),
        camp_rounds,
        rounds_per_dispatch=spec.rounds_per_dispatch,
        collect_decisions=True,
        config=SupervisorConfig(timeout_s=60.0),
    )
    got = adopted[0].result
    migrated_bit_exact = (
        np.array_equal(want["decisions"], got["decisions"])
        and np.array_equal(want["histograms"], got["histograms"])
        and got["supervisor"]["history_start"] == 0
    )
    assert migrated_bit_exact, (
        "adopted campaign diverged from the uninterrupted same-seed "
        "run (or lost reassembled history)"
    )
    assert single_rpc == 0 and fleet_rpc == 0, (
        f"request-path compiles: single={single_rpc} fleet={fleet_rpc} "
        f"(ring entry must be warm-gated)"
    )
    over = {n: d for n, d in peak.items() if d > max_queue}
    assert not over, f"queue bound {max_queue} exceeded: {over}"
    shutil.rmtree(fleet_root)  # asserts passed — a failing run keeps it
    fleet_p50, fleet_p99 = pcts(lat)

    return {
        "rounds_per_sec": round(len(requests) * rounds / t_fleet, 1),
        "clients": clients,
        "requests": len(requests),
        "rounds": rounds,
        "max_batch": max_batch,
        "max_queue": max_queue,
        "replicas": 3,
        "victim": victim,
        "campaign_rounds": camp_rounds,
        "single_warmup_wall_s": round(t_warm_single, 4),
        "single_elapsed_s": round(t_single, 4),
        "single_p50_latency_s": round(single_p50, 4),
        "single_p99_latency_s": round(single_p99, 4),
        "fleet_warmup_wall_s": round(t_warm_fleet, 4),
        "fleet_elapsed_s": round(t_fleet, 4),
        "fleet_p50_latency_s": round(fleet_p50, 4),
        "fleet_p99_latency_s": round(fleet_p99, 4),
        "routes": rstats["routes"],
        "reroutes": rstats["reroutes"],
        "peak_queue_depths": peak,
        "reroute_zero_hung_clients": not err and not mismatch,
        "migrated_bit_exact": migrated_bit_exact,
        "no_request_path_compiles_fleet": (
            single_rpc == 0 and fleet_rpc == 0
        ),
        "queue_bounded_all_replicas": not over,
        "bound": "one cohort, so the whole fleet's traffic hashes to "
                 "ONE home replica — killing it mid-run is the "
                 "worst-case membership change; every boolean is "
                 "asserted, a regression fails the bench rather than "
                 "flipping a committed boolean",
        "note": "the kill fires 50ms into the client storm (queued "
                "tickets fail and re-home via RoutedTicket inside the "
                "caller's original timeout); the abandoned campaign "
                "leaves only fsync'd ledger rows + periodic "
                "checkpoints, and adopt_orphans resumes it "
                "fingerprint-verified on a survivor, bit-exact vs the "
                "uninterrupted same-seed run",
    }


_MULTICHIP_CHILD = r'''
import dataclasses, hashlib, json, sys, time

import numpy as np

import jax
import jax.random as jr

from ba_tpu.parallel import fresh_copy, make_mesh, make_sweep_state
from ba_tpu.parallel.pipeline import scenario_sweep
from ba_tpu.scenario.compile import block_from_kills

cfg = json.loads(sys.argv[1])
b0, cap, rounds, kpd = cfg["b0"], cfg["cap"], cfg["rounds"], cfg["kpd"]


def digest(*arrays):
    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a))
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def campaign(batch):
    rng = np.random.default_rng(41)
    kills = rng.random((rounds, batch, cap)) < 0.02
    state = make_sweep_state(jr.key(40), batch, cap)
    return state, block_from_kills(kills)


def run(batch, mesh, state, block, **kw):
    return scenario_sweep(
        jr.key(42), fresh_copy(state), block,
        rounds_per_dispatch=kpd, collect_decisions=True, mesh=mesh,
        **kw,
    )


try:
    if cfg["role"] == "resume":
        # Reshard-on-read leg: resume the d=8 checkpoint on a (d',1)
        # mesh in THIS process (device count forced smaller via
        # XLA_FLAGS by the parent) and report the tail digest.
        d = cfg["d"]
        mesh = make_mesh((d, 1), ("data", "node")) if d > 1 else None
        state, block = campaign(cfg["batch"])
        tail = scenario_sweep(
            None, None, block, rounds_per_dispatch=kpd,
            collect_decisions=True, mesh=mesh, resume=cfg["ckpt"],
        )
        print(json.dumps({
            "devices": len(jax.devices()),
            "tail_digest": digest(
                tail["decisions"], tail["leaders"],
                tail["counters_per_round"],
            ),
            "counters": tail["counters"],
        }))
        sys.exit(0)

    result = {"devices": len(jax.devices())}

    # -- bit-exactness at EQUAL shapes: d=1 vs d=8, same key/campaign --
    state, block = campaign(b0)
    mesh8 = make_mesh((8, 1), ("data", "node"))
    plain = run(b0, None, state, block)
    sharded = run(b0, mesh8, state, block)
    same = (
        (plain["decisions"] == sharded["decisions"]).all()
        and (plain["leaders"] == sharded["leaders"]).all()
        and (plain["counters_per_round"]
             == sharded["counters_per_round"]).all()
        and (plain["histograms"] == sharded["histograms"]).all()
    )
    result["parity"] = {
        "bit_exact": bool(same),
        "batch": b0,
        "counters": plain["counters"],
    }

    # -- weak scaling: B grows with d; per-device bytes must not -------
    legs = []
    for d in cfg["scaling_d"]:
        batch = b0 * d
        mesh = make_mesh((d, 1), ("data", "node")) if d > 1 else None
        state, block = campaign(batch)
        states = [fresh_copy(state) for _ in range(3)]
        run(batch, mesh, states[0], block)  # warm/compile off the clock
        t_best = float("inf")
        for r in range(2):
            t0 = time.perf_counter()
            out = run(batch, mesh, states[1 + r], block)
            t_best = min(t_best, time.perf_counter() - t0)
        st = out["stats"]
        legs.append({
            "d": d, "batch": batch,
            "elapsed_s": round(t_best, 4),
            "rounds_per_sec": round(batch * rounds / t_best, 1),
            "plane_peak_bytes": st["plane_peak_bytes"],
            "plane_peak_bytes_per_shard": st["plane_peak_bytes_per_shard"],
            "carry_bytes_per_shard": st["carry_bytes_per_shard"],
        })
    result["weak_scaling"] = legs

    # -- checkpoint on d=8 for the parent's d' resume leg --------------
    state, block = campaign(cfg["batch_ckpt"])
    full = run(cfg["batch_ckpt"], mesh8, state, block)
    ck_round = (rounds // 2) // kpd * kpd
    state, block = campaign(cfg["batch_ckpt"])
    run(
        cfg["batch_ckpt"], mesh8, state, block,
        checkpoint_every=ck_round, checkpoint_path=cfg["ckpt"],
    )
    result["checkpoint"] = {
        "written_on_d": 8,
        "round": ck_round,
        "tail_digest": digest(
            full["decisions"][ck_round:], full["leaders"][ck_round:],
            full["counters_per_round"][ck_round:],
        ),
        "counters": full["counters"],
    }
    print(json.dumps(result))
except ValueError as e:
    # One line, never a traceback: the parent surfaces mesh/layout
    # errors (e.g. an oversized make_mesh request) as a skip reason.
    print(json.dumps({"error": str(e)}))
    sys.exit(3)
'''


def bench_multichip(jax, jnp, jr):
    """Mesh-sharded engine A/B on a forced 8-device CPU mesh (ISSUE 8
    acceptance; the committed artifact is MULTICHIP_r06.json).  Three
    pins:

    1. **Bit-exactness at equal shapes** — the same campaign (key,
       states, kill schedule) through the single-device engine and the
       8×1 ``shard_map`` engine: decisions, leaders, histograms and
       every counter row must match bit-for-bit.
    2. **Weak scaling** — B grows with the device count (d in {1, 2, 8},
       B = B0·d) while per-device peak plane/carry bytes stay bounded by
       the B0 figure (the 1/d memory claim — deterministic, asserted);
       wall time is reported per leg with the host's physical core count
       attached, because 8 VIRTUAL cpu devices cannot beat the machine's
       real parallelism (the flat-wall-time reading needs >= d cores —
       on TPU, d chips).
    3. **Checkpoint reshard** — a campaign checkpointed mid-flight on
       d=8 resumes on d'=2 in a separate 2-device process
       (gather-on-write / reshard-on-read), tail bit-identical to the
       uninterrupted run.

    Every leg runs in a child process: the device count
    (``--xla_force_host_platform_device_count``, the exact layout
    tests/multihost_worker.py uses) must be fixed before jax
    initializes.
    """
    import subprocess
    import tempfile

    b0 = int(os.environ.get("BA_TPU_BENCH_MC_BATCH", 256))
    cap = int(os.environ.get("BA_TPU_BENCH_MC_CAP", 16))
    rounds = int(os.environ.get("BA_TPU_BENCH_MC_ROUNDS", 64))
    kpd = int(os.environ.get("BA_TPU_BENCH_MC_KPD", 8))
    batch_ckpt = b0 // 4 * 8  # d=8-divisible, small enough for d'=2 leg

    def child(n_devices, cfg, timeout):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={n_devices}",
        )
        # The virtual-device flag must not collide with an inherited one.
        proc = subprocess.run(
            [sys.executable, "-c", _MULTICHIP_CHILD, json.dumps(cfg)],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            tail = (proc.stdout + proc.stderr).strip().splitlines()
            line = tail[-1] if tail else "no output"
            try:
                line = json.loads(line).get("error", line)
            except ValueError:
                pass
            # One line, never a traceback (ISSUE 8 satellite).
            print(f"bench: multichip leg failed: {line}", file=sys.stderr)
            return None
        return json.loads(proc.stdout.strip().splitlines()[-1])

    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "mc_{round}.npz")
        base = {
            "b0": b0, "cap": cap, "rounds": rounds, "kpd": kpd,
            "ckpt": ckpt, "batch_ckpt": batch_ckpt,
        }
        main = child(
            8, dict(base, role="main", scaling_d=[1, 2, 8]), timeout=1800
        )
        if main is None:
            return {"skipped": "multichip main leg failed (see stderr)"}
        resume = child(
            2,
            dict(base, role="resume", d=2, batch=batch_ckpt,
                 ckpt=ckpt.replace(
                     "{round}", str(main["checkpoint"]["round"]))),
            timeout=900,
        )
    legs = {leg["d"]: leg for leg in main["weak_scaling"]}
    reshard_exact = (
        resume is not None
        and resume["tail_digest"] == main["checkpoint"]["tail_digest"]
    )
    return {
        # Headline rate = the full-mesh leg (bench.py's primary-config
        # contract expects one).
        "rounds_per_sec": legs[8]["rounds_per_sec"],
        "devices": main["devices"],
        "host_cpus": os.cpu_count(),
        "bit_exact_d1_vs_d8": main["parity"]["bit_exact"],
        "weak_scaling": main["weak_scaling"],
        "wall_ratio_d8_vs_d1_at_8x_batch": round(
            legs[8]["elapsed_s"] / legs[1]["elapsed_s"], 3
        ),
        "wall_ratio_d2_vs_d1_at_2x_batch": round(
            legs[2]["elapsed_s"] / legs[1]["elapsed_s"], 3
        ),
        "plane_bytes_per_shard_bounded_by_B_over_d": all(
            leg["plane_peak_bytes_per_shard"] <= legs[1]["plane_peak_bytes"]
            for leg in main["weak_scaling"]
        ),
        "carry_bytes_per_shard_bounded_by_B_over_d": all(
            leg["carry_bytes_per_shard"] <= legs[1]["carry_bytes_per_shard"]
            + 64  # replicated 12-byte schedule + [d,C] counter rows
            for leg in main["weak_scaling"]
        ),
        "checkpoint_reshard_d8_to_d2_bit_exact": bool(reshard_exact),
        "checkpoint_round": main["checkpoint"]["round"],
        "rounds": rounds, "b0": b0, "n_max": cap,
        "rounds_per_dispatch": kpd,
        "scenario_counters_d1": main["parity"]["counters"],
        "bound": "per-device memory: staged event planes and the donated "
                 "carry split B/d per chip (asserted); wall time: weak "
                 "scaling is flat only up to the host's REAL parallelism "
                 "— 8 virtual CPU devices share host_cpus cores here, so "
                 "the d=8 leg measures sharding overhead at core "
                 "saturation, not chip scaling (the TPU reading is d "
                 "real chips)",
        "note": "all legs in child processes (the forced device count "
                "must precede jax init); bit-exactness = decisions + "
                "leaders + histograms + all counter rows compared "
                "elementwise at equal shapes; reshard = sha256 over the "
                "resumed tail's decisions/leaders/counter rows vs the "
                "uninterrupted d=8 run",
    }


def bench_failover_sweep(jax, jnp, jr):
    """On-device failure detection + re-election throughput (VERDICT r3
    weak #6: the subsystem was tested and dry-run but never measured).

    R rounds of kill -> detect dead leader -> re-elect lowest alive id ->
    agree, all inside ONE lax.scan dispatch (``parallel.failover_sweep``
    — the tensor-scale form of the reference's 0.1 s detect->elect loop,
    ba.py:306-314), A/B'd same-window against the identical R-round OM(1)
    scan WITHOUT the kill/election stage, so the reported overhead is the
    re-election machinery itself, not window weather.  Kill schedule:
    each node dies with p=2% per round (pre-staged on device, off the
    clock), so most instances re-elect at least once across R rounds.
    """
    from ba_tpu.core import make_state
    from ba_tpu.core.om import om1_round
    from ba_tpu.core.quorum import majority_counts, quorum_decision
    from ba_tpu.core.types import ATTACK
    from ba_tpu.parallel import failover_sweep

    batch = int(os.environ.get("BA_TPU_BENCH_FAILOVER_BATCH", 8192))
    n, R, m = 64, 16, 1
    faulty = jnp.zeros((batch, n), bool).at[:, 5].set(True)
    state = make_state(batch, n, order=ATTACK, faulty=faulty)
    # ~2%/node/round crash schedule; node 0 starts as leader, so a fair
    # share of instances lose their leader mid-scan and re-elect.
    import jax.random as _jr

    kills = _jr.bernoulli(make_key(12), 0.02, (R, batch, n))

    @jax.jit
    def fail_step(key):  # state/kills closed over (seed-only dispatch)
        out = failover_sweep(key, state, kills, m=m)
        return (
            out["decisions"].astype(jnp.int32).sum()
            + out["leaders"].sum()
        )

    @jax.jit
    def plain_step(key):
        def one(acc, k):
            majorities = om1_round(k, state)
            n_a, n_r, n_u = majority_counts(majorities, state.alive)
            d, _, _ = quorum_decision(n_a, n_r, n_u)
            return acc + d.astype(jnp.int32).sum(), None

        acc, _ = jax.lax.scan(one, jnp.int32(0), jr.split(key, R))
        return acc

    key = make_key(13)
    jax.device_get(fail_step(key))  # compile/warm off the clock
    jax.device_get(plain_step(key))
    iters, reps = 10, 3
    t_fail = t_plain = float("inf")
    for r in range(reps):  # interleaved: drift cancels
        t_fail = min(t_fail, _timed(
            fail_step, lambda i, _r=r: (jr.fold_in(key, 2 * (_r * iters + i)),),
            iters, reps=1,
        ))
        t_plain = min(t_plain, _timed(
            plain_step,
            lambda i, _r=r: (jr.fold_in(key, 2 * (_r * iters + i) + 1),),
            iters, reps=1,
        ))
    rounds = batch * R * iters
    bytes_round = batch * (2 * n * n + 5 * n + n)  # om1 cubes + kill plane
    return {
        "rounds_per_sec": round(rounds / t_fail, 1),
        "plain_rounds_per_sec": round(rounds / t_plain, 1),
        "reelection_overhead_pct": round(100 * (t_fail - t_plain) / t_plain, 1),
        "batch": batch, "n": n, "m": m, "rounds_per_dispatch": R,
        "iters": iters, "elapsed_s": round(t_fail, 4),
        "kill_prob_per_round": 0.02,
        "bytes_per_round_est": bytes_round,
        "achieved_gbps_est": round(bytes_round * R * iters / t_fail / 1e9, 2),
        "bound": "VPU elementwise (om1 answer cubes) + scan-carried "
                 "alive/leader state; reference analogue: one detect->"
                 "elect cycle per 0.1 s poll tick (ba.py:306-314)",
        "note": "A/B same-window: plain = the identical R-round OM(1) "
                "scan without kill/election; overhead pct is fail vs "
                "plain",
    }


def bench_interactive_b1(jax, jnp, jr):
    """Interactive single-cluster latency: one ``actual-order`` round at
    B=1, each dispatch individually host-synced — the case the reference
    answers in ~0.2-0.3 s of wall-poll time (wait_majority + run-loop
    ticks, ba.py:287-301).  Through the shared TPU tunnel a round pays the
    full dispatch+fetch latency, so this is a *latency* number (per-round,
    not amortizable); the batched configs are where the framework wins,
    and this config owns that trade with a measured figure (VERDICT r2
    weak #3)."""
    from ba_tpu.core import make_state, om1_agreement
    from ba_tpu.core.types import ATTACK

    n = 7
    faulty = jnp.zeros((1, n), bool).at[:, 3].set(True)
    state = make_state(1, n, order=ATTACK, faulty=faulty)

    @jax.jit
    def step(key, state):
        out = om1_agreement(key, state)
        return out["decision"].astype(jnp.int32).sum(), out["needed"].sum()

    key = make_key(9)
    jax.device_get(step(key, state))  # compile off the clock
    times = []
    for i in range(1, 21):
        t0 = time.perf_counter()
        jax.device_get(step(jr.fold_in(key, i), state))
        times.append(time.perf_counter() - t0)
    times.sort()
    med = times[len(times) // 2]
    return {
        "round_latency_median_s": round(med, 4),
        "round_latency_p10_s": round(times[1], 4),
        "round_latency_p90_s": round(times[-2], 4),
        "rounds": len(times), "n": n, "batch": 1,
        "reference_latency_s": "~0.2-0.3 (poll-loop floor, ba.py:287-301)",
        "bound": "per-dispatch tunnel latency (~50-100 ms), not compute",
    }


def make_fieldmul_probe(jax, jnp, jr):
    """Synthetic field-multiply chain probe: a measured FLOOR on
    attainable GF(2^255-19) throughput, in the verify's own unit.

    VERDICT r3 weak #3: the old roofline divided verify's estimated raw
    int32 multiplies by a separately-measured VPU multiply peak — two
    different units measured in two different service windows, which
    produced 108-198% "of peak" depending on the weather.  This probe
    runs the SAME ``p_mul`` plane primitive the production kernels use
    (ba_tpu.ops.planes) inside one Pallas kernel at full VMEM occupancy.
    Measured r4: even with 8 independent chains x 2-deep unroll it tops
    out ~2x BELOW the window-ladder kernel's per-mul rate — compound
    point formulas expose cross-mul ILP a synthetic chain cannot — so
    the verify roofline denominator is the interleaved ladder leg in
    bench_sm1_n64_signed, and this probe is reported as the floor.

    Returns (fn, variants, fieldmuls_per_dispatch); fn is jitted and
    returns a scalar (host-fetch-sync contract of ``_timed``), and
    ``variants`` is a list of DEVICE-resident input tuples — staged here,
    outside any timed loop, so probe dispatches never pay a host->device
    upload through the tunnel (trap: multi-MB uploads inside timed loops
    dominate silently).  Content differs per variant (tunnel memoization).
    On non-Pallas backends the probe chains ``crypto.field.mul`` instead
    (same unit, XLA discipline).
    """
    import numpy as np

    from ba_tpu.crypto import field as F
    from ba_tpu.utils.platform import use_pallas

    depth = 512
    rng = np.random.default_rng(11)

    if use_pallas():
        from jax.experimental import pallas as pl
        from ba_tpu.ops.ladder import plane_spec, plane_out_shape, TILE
        from ba_tpu.ops.planes import p_mul

        lanes = 1 << 16  # 64 [8, 128] tiles

        # FOUR independent mul chains per lane x FOUR muls per chain per
        # loop iteration.  A single dependent chain measures VPU latency,
        # not throughput (first cut: the verify pipeline "achieved" 217%
        # of that "peak"); and at few muls per iteration the fori_loop's
        # carried state (chains x 22 planes) round-trips VMEM often
        # enough to dominate (second cut: still 181%).  16 muls per
        # carried-state exchange matches the ladder kernel's regime
        # (~17 muls per 2-point-add step).
        chains, unroll = 8, 2

        def kernel(a_ref, b_ref, o_ref):
            b = [b_ref[i] for i in range(F.LIMBS)]
            accs = [
                [a_ref[i] + jnp.int32(c) for i in range(F.LIMBS)]
                for c in range(chains)
            ]

            def body(t, accs):
                for _ in range(unroll):
                    accs = [p_mul(acc, b) for acc in accs]
                return accs

            accs = jax.lax.fori_loop(
                0, depth // (chains * unroll), body, accs
            )
            for i in range(F.LIMBS):
                o_ref[i] = sum(acc[i] for acc in accs)

        grid = lanes // TILE

        @jax.jit
        def fn(a, b):
            out = pl.pallas_call(
                kernel,
                grid=(grid,),
                in_specs=[plane_spec(F.LIMBS)] * 2,
                out_specs=plane_spec(F.LIMBS),
                out_shape=plane_out_shape(F.LIMBS, lanes),
            )(a, b)
            return out.astype(jnp.int32).sum()

        def make_variant():
            a = rng.integers(0, 1 << 12, (F.LIMBS, lanes // 128, 128))
            b = rng.integers(0, 1 << 12, (F.LIMBS, lanes // 128, 128))
            return jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32)

    else:
        lanes = 1 << 12  # CPU fallback: unit-correct, not a perf claim

        @jax.jit
        def fn(a, b):
            def body(t, acc):
                return F.mul(acc, b)

            return jax.lax.fori_loop(0, depth, body, a).astype(
                jnp.int32
            ).sum()

        def make_variant():
            a = rng.integers(0, 1 << 12, (lanes, F.LIMBS))
            b = rng.integers(0, 1 << 12, (lanes, F.LIMBS))
            return jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32)

    n_variants = int(os.environ.get("BA_TPU_FMUL_PROBE_VARIANTS", 12))
    variants = [make_variant() for _ in range(n_variants)]
    return fn, variants, lanes * depth


def bench_fieldmul_peak(jax, jnp, jr):
    """Standalone field-mul probe timing (see make_fieldmul_probe) for the
    --stages artifact; bench_sm1 interleaves the same probe with its
    verify reps instead of calling this."""
    fn, variants, per_dispatch = make_fieldmul_probe(jax, jnp, jr)
    iters = 3
    elapsed = _timed(
        fn, lambda i: variants[i % len(variants)], iters, reps=3
    )
    per_sec = per_dispatch * iters / elapsed
    return {
        "measured_fieldmuls_per_sec": round(per_sec, 1),
        "gmults_equiv_per_sec": round(per_sec * 484 / 1e9, 1),
        "fieldmuls_per_dispatch": per_dispatch,
        "elapsed_s": round(elapsed, 4),
        "note": "chained ops.planes.p_mul (schoolbook 484-MAC + "
                "reduce/carry) at full VMEM occupancy — a measured FLOOR "
                "on attainable field-mul throughput; the production "
                "kernels exceed it ~2x via cross-mul ILP, so the verify "
                "roofline divides by the same-window window-ladder rate "
                "instead (bench_sm1_n64_signed.pct_of_ladder_rate)",
    }


def bench_vpu_int32_peak(jax, jnp, jr):
    """Measured attainable int32 multiply(-add) throughput — the roofline
    denominator for the Ed25519 verify kernel's est_int32_gmults_per_sec
    (VERDICT r2: '720 Gmult/s' had no measured peak to be compared with).

    A [4M]-lane int32 multiply-add chain: 256 steps UNROLLED at trace time
    (so XLA fuses them into one register-resident kernel — the r3-first-cut
    pure-``fori_loop`` version re-read HBM every step and measured
    bandwidth, 94.5 "Gmult/s") wrapped in a 16-iteration fori_loop for
    ~17G mults per dispatch (the pure-unrolled second cut did ~0.27G, small
    enough that the ~15 ms tunnel dispatch latency dominated and "peak"
    came out at 18 Gmult/s).  The multiplier is the data-dependent lane
    value itself, so strength-reduction to shifts is impossible; content
    varies per dispatch (tunnel memoization).
    """
    lanes, inner, outer = 1 << 22, 256, 16

    @jax.jit
    def f(x):
        def body(_, v):
            for _ in range(inner):
                v = v * x + jnp.int32(1013904223)
            return v
        return jax.lax.fori_loop(0, outer, body, x).astype(jnp.int32).sum()

    key = make_key(7)
    iters = 4
    elapsed = _timed(
        f, lambda i: (jr.randint(jr.fold_in(key, i), (lanes,), 0, 1 << 30,
                                 jnp.int32),), iters
    )
    gmults = lanes * inner * outer * iters / elapsed / 1e9
    return {
        "measured_gmults_per_sec": round(gmults, 1),
        "lanes": lanes, "depth_per_dispatch": inner * outer, "iters": iters,
        "elapsed_s": round(elapsed, 4),
        "note": "unrolled register-resident int32 mul+add chain (256-deep "
                "fused blocks x16), data-dependent multiplier; the VPU "
                "peak an elementwise kernel can hope for (MXU not "
                "reachable for per-lane dynamic bignum products)",
    }


def bench_verify_stages(jax, jnp, jr):
    """Host-fetch-timed per-stage breakdown of the Ed25519 verify pipeline
    (VERDICT r2 missing #3: the 423k verifies/s number could not be
    attributed or regression-localized; the dev-time harness that produced
    the docstring stage numbers was not kept).

    Stages mirror ``crypto.ed25519.verify`` at the production chunk size:
    sha512 -> mod-L reduce -> decompress (2B lanes) -> window ladder [h]A
    -> fixed-base [S]B -> the finishing adds/equality.  Each stage is
    timed as its own jitted program on realistic intermediates with
    content varied per dispatch; per-dispatch tunnel latency (~50-100 ms)
    is why iters are amortized.  sum_of_stages ~ full_verify is the
    cross-check that the decomposition covers the pipeline.

    Every stage input is staged on DEVICE before its timed loop: the
    r3-first-cut harness built inputs inside make_args, so each dispatch
    paid a multi-MB host->device upload through the tunnel (the ladder's
    four 5.5 MB planes "timed" at 3.5 s/dispatch against a 141 ms full
    verify — a 37x phantom).  sum_of_stages vs full_verify is the guard
    that catches any regression of this kind.
    """
    import numpy as np

    from ba_tpu.crypto import field as F
    from ba_tpu.crypto.ed25519 import (
        decompress,
        fixed_base_mult,
        point_add,
        point_eq,
        verify,
        _use_pallas,
    )
    from ba_tpu.crypto.sha512 import sha512
    from ba_tpu.crypto.signed import _verify_chunk, commander_keys, sign_received

    nv = int(os.environ.get("BA_TPU_BENCH_VERIFY_BATCH", 0)) or _verify_chunk()
    rng = np.random.default_rng(5)

    # Real signed content, tiled to the chunk; V distinct variants so that
    # EVERY timed dispatch (reps*iters + warmup, cycling i % V) sees fresh
    # content — device-resident buffers re-dispatched byte-identically get
    # memoized by the tunnel backend and time ~0.
    batch, n = 64, 64
    sks, pks = commander_keys(batch)
    tile = -(-nv // (batch * n))
    iters, reps = 3, 2
    V = reps * iters + 2  # warmup uses i=0; reps cycle i=1..reps*iters
    variants = []
    for v in range(V):
        received = rng.integers(0, 2, (batch, n))
        msgs, sigs = sign_received(sks, pks, received)
        pk_flat = np.tile(np.repeat(pks, n, axis=0), (tile, 1))[:nv]
        msg_flat = np.tile(msgs.reshape(batch * n, -1), (tile, 1))[:nv]
        sig_flat = np.tile(sigs.reshape(batch * n, 64), (tile, 1))[:nv]
        variants.append(
            (jnp.asarray(pk_flat), jnp.asarray(msg_flat), jnp.asarray(sig_flat))
        )

    results = {}

    def timed(name, fn, make_args):
        elapsed = _timed(fn, make_args, iters, reps=reps)
        per_sig_ns = elapsed / iters / nv * 1e9
        results[name] = {
            "ms_per_dispatch": round(elapsed / iters * 1e3, 2),
            "ns_per_sig": round(per_sig_ns, 1),
        }
        return elapsed / iters

    # Stage inputs: computed once per variant AND left device-resident, so
    # the timed loops dispatch against buffers already on the chip.
    def h_input(v):
        pk, msg, sig = variants[v]
        return jnp.concatenate([sig[..., :32], pk, msg], axis=-1)

    t_total = 0.0

    sha_in = [h_input(v) for v in range(V)]
    fn_sha = jax.jit(lambda x: sha512(x).astype(jnp.int32).sum())
    t_total += timed("sha512", fn_sha, lambda i: (sha_in[i % V],))

    modl_in = [jax.jit(sha512)(sha_in[v]) for v in range(V)]
    if _use_pallas():
        from ba_tpu.ops.modl import reduce_mod_l_planes as _modl
    else:
        from ba_tpu.crypto.scalar import reduce_mod_l as _modl
    fn_modl = jax.jit(lambda h: _modl(h).astype(jnp.int32).sum())
    t_total += timed("mod_l", fn_modl, lambda i: (modl_in[i % V],))

    dec_in = [
        jnp.concatenate([variants[v][0], variants[v][2][..., :32]], axis=0)
        for v in range(V)
    ]
    fn_dec = jax.jit(
        lambda by: sum(c.astype(jnp.int32).sum() for c in decompress(by)[0])
    )
    t_total += timed("decompress_2B", fn_dec, lambda i: (dec_in[i % V],))

    # Ladder inputs: decompressed A points + reduced h bits (one per variant).
    lad_in = []
    for v in range(V):
        pk, msg, sig = variants[v]
        pts, _ = jax.jit(decompress)(pk)
        hb = jax.jit(lambda h: F.bytes_to_bits(_modl(h)))(modl_in[v])
        lad_in.append((pts, hb))
    if _use_pallas():
        from ba_tpu.ops.ladder import window_mult as _lmult
    else:
        from ba_tpu.crypto.ed25519 import scalar_mult as _lmult
    fn_lad = jax.jit(
        lambda pt, bits: sum(
            c.astype(jnp.int32).sum() for c in _lmult(pt, bits)
        )
    )
    t_total += timed("ladder_hA", fn_lad, lambda i: lad_in[i % V])

    fb_in = [variants[v][2][..., 32:] for v in range(V)]
    fn_fb = jax.jit(
        lambda s: sum(c.astype(jnp.int32).sum() for c in fixed_base_mult(s))
    )
    t_total += timed("fixed_base_sB", fn_fb, lambda i: (fb_in[i % V],))

    # Finish: R + [h]A == [S]B — exactly one add + one projective equality,
    # with three DISTINCT precomputed points (a symmetric-operand form
    # would let XLA CSE the adds and time nothing).
    fin_in = []
    for v in range(V):
        pk, msg, sig = variants[v]
        r_pts, _ = jax.jit(decompress)(sig[..., :32])
        ha = lad_in[v][0]  # stand-in [h]A (device-resident)
        sb = jax.jit(fixed_base_mult)(sig[..., 32:])  # the real [S]B
        fin_in.append((r_pts, ha, sb))
    fn_fin = jax.jit(
        lambda r_pt, ha, sb: point_eq(
            sb, point_add(r_pt, ha)
        ).astype(jnp.int32).sum()
    )
    t_total += timed("finish_add_eq", fn_fin, lambda i: fin_in[i % V])

    fn_full = jax.jit(lambda p, m, s: verify(p, m, s).astype(jnp.int32).sum())
    t_full = timed("full_verify", fn_full, lambda i: variants[i % V])

    results["sum_of_stages_ms"] = round(t_total * 1e3, 2)
    results["full_verify_ms"] = round(t_full * 1e3, 2)
    results["verify_batch"] = nv
    results["verifies_per_sec_full"] = round(nv / t_full, 1)
    return results


_obs_finalized = False


def _obs_finalize(obs_dir: str, platform: str) -> None:
    """Flush the obs layer into DIR: one metrics_snapshot JSONL record
    (depth occupancy, dispatch/retire latency and compile-time histogram
    buckets, counters), the Chrome trace, and Prometheus text.

    Idempotent: also registered atexit by --obs setup, so a crashed or
    Ctrl-C'd run still gets its partial trace/snapshot — the run you
    most want the artifacts for."""
    global _obs_finalized
    if _obs_finalized:
        return
    _obs_finalized = True
    from ba_tpu import obs

    reg = obs.default_registry()
    reg.emit_snapshot(platform=platform)
    # Flight summary (ISSUE 9): join the run's JSONL stream — every
    # config's dispatch windows, checkpoints, recompiles — into one
    # flight_summary record appended to metrics.jsonl (the whole bench
    # invocation is ONE run scope, established at --obs setup, so
    # per-config sweeps inherit instead of emitting per-sweep
    # summaries).  Render with scripts/obs_report.py DIR --flight.
    obs.flight.emit_flight_summary()
    obs.default_tracer().export_chrome(os.path.join(obs_dir, "trace.json"))
    with open(os.path.join(obs_dir, "metrics.prom"), "w") as f:
        f.write(reg.prometheus_text())


def bench_megastep_ab(jax, jnp, jr):
    """ISSUE 13: the one-kernel mutating round A/B — three legs over an
    IDENTICAL strategy-mixed churn campaign, bit-exactness asserted
    between every pair before any timing is believed:

    1. ``xla_chain``      — the XLA scan core with the PRE-ISSUE-13
       nested-select strategy formulation (``strategies.chain_impl()``
       re-traces it; the megastep jit cache is cleared so the flag is
       seen at trace time).  The historical baseline.
    2. ``xla_branchfree`` — the XLA scan core with the branch-free
       lie-table strategies (today's default).  chain -> branchfree is
       the CPU-measurable part of the ISSUE: the select-chain
       pathology removed at equal semantics.
    3. ``kernel``         — the fused Pallas megastep
       (``ops/scenario_step.py``) via ``engine="pallas"``: Mosaic on a
       real TPU (the raw-speed goal's leg — <4x vs the fused sweep
       kernel rides the consolidated tunnel pass), the interpreter
       elsewhere (the leg still proves end-to-end dispatch + bit
       parity; its CPU wall clock is the INTERPRETER's and is reported
       as such, never as kernel speed).

    All three legs run the same ``scenario_sweep`` driver — depth-k
    retires, donated carries, staged planes — so the deltas are the
    round formulation only.  Campaign: every strategy id present, ~2%
    kills + 1% revives + strategy churn per round.
    """
    import numpy as np

    from ba_tpu.parallel import fresh_copy, make_sweep_state, scenario_sweep
    from ba_tpu.parallel.pipeline import scenario_megastep
    from ba_tpu.scenario import strategies as strat_mod
    from ba_tpu.scenario.compile import ScenarioBlock

    # The scenario_sweep production shape (BENCH_scenario_r8.json):
    # the strategy pathology only shows where the answer cube is real
    # work — small shapes are dispatch-overhead-dominated and read ~1x.
    batch = int(os.environ.get("BA_TPU_BENCH_MEGA_BATCH", 2048))
    cap = int(os.environ.get("BA_TPU_BENCH_MEGA_CAP", 64))
    rounds = int(os.environ.get("BA_TPU_BENCH_MEGA_ROUNDS", 64))
    per_dispatch = int(os.environ.get("BA_TPU_BENCH_MEGA_KPD", 8))
    depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
    reps = 3

    state = make_sweep_state(make_key(40), batch, cap)
    rng = np.random.default_rng(41)
    strat0 = jnp.asarray(rng.integers(0, 5, (batch, cap)).astype(np.int8))
    block = ScenarioBlock(
        kill=rng.random((rounds, batch, cap)) < 0.02,
        revive=rng.random((rounds, batch, cap)) < 0.01,
        set_faulty=np.full((rounds, batch, cap), -1, np.int8),
        set_strategy=np.where(
            rng.random((rounds, batch, cap)) < 0.05,
            rng.integers(0, 5, (rounds, batch, cap)), -1
        ).astype(np.int8),
    )

    def run(st, engine):
        return scenario_sweep(
            make_key(42), st, block, initial_strategy=strat0,
            depth=depth, rounds_per_dispatch=per_dispatch,
            collect_decisions=True, engine=engine,
        )

    def leg_outputs(out):
        return (
            out["decisions"], out["leaders"], out["histograms"],
            out["counters_per_round"],
            np.asarray(out["final_strategy"]),
        )

    def identical(a, b):
        return all(
            np.array_equal(x, y) for x, y in zip(leg_outputs(a), leg_outputs(b))
        )

    # 3 warm/verify runs + 5 per rep (chain re-trace warm, chain timed,
    # branch-free re-trace warm, branch-free timed, kernel timed).
    n_states = 3 + 5 * reps
    states = [fresh_copy(state) for _ in range(n_states)]
    si = iter(states)

    # Warm every leg off the clock (compiles + verification outputs).
    # The chain leg re-traces the legacy formulation: the flag is read
    # at trace time, so the megastep cache clears around it — and again
    # after, so the branch-free legs never reuse a chain trace.
    scenario_megastep.clear_cache()
    with strat_mod.chain_impl():
        out_chain = run(next(si), "xla")
    scenario_megastep.clear_cache()
    out_bf = run(next(si), "xla")
    out_kernel = run(next(si), "pallas")
    kernel_engine = out_kernel["stats"]["engine"]
    bit_chain = identical(out_chain, out_bf)
    bit_kernel = identical(out_kernel, out_bf)
    assert bit_chain, "chain vs branch-free diverged — A/B is meaningless"
    assert bit_kernel, (
        "kernel engine vs XLA core diverged — A/B is meaningless"
    )

    t = {"xla_chain": float("inf"), "xla_branchfree": float("inf"),
         "kernel": float("inf")}
    for _ in range(reps):  # interleaved: window drift cancels
        scenario_megastep.clear_cache()
        with strat_mod.chain_impl():
            run(next(si), "xla")  # chain re-trace compile, off the clock
            t0 = time.perf_counter()
            run(next(si), "xla")
            t["xla_chain"] = min(t["xla_chain"], time.perf_counter() - t0)
        scenario_megastep.clear_cache()
        run(next(si), "xla")  # branch-free re-trace, off the clock
        t0 = time.perf_counter()
        run(next(si), "xla")
        t["xla_branchfree"] = min(
            t["xla_branchfree"], time.perf_counter() - t0
        )
        t0 = time.perf_counter()
        run(next(si), "pallas")
        t["kernel"] = min(t["kernel"], time.perf_counter() - t0)

    rps = {k: round(batch * rounds / v, 1) for k, v in t.items()}
    return {
        "rounds_per_sec": rps["xla_branchfree"],
        "chain_rounds_per_sec": rps["xla_chain"],
        "kernel_rounds_per_sec": rps["kernel"],
        "kernel_engine": kernel_engine,
        "branchfree_speedup_vs_chain": round(
            t["xla_chain"] / t["xla_branchfree"], 3
        ),
        "kernel_ratio_vs_branchfree": round(
            t["xla_branchfree"] / t["kernel"], 3
        ),
        "bit_exact_chain_vs_branchfree": bool(bit_chain),
        "bit_exact_kernel_vs_xla": bool(bit_kernel),
        "batch": batch, "n_max": cap, "rounds": rounds,
        "rounds_per_dispatch": per_dispatch, "depth": depth,
        "elapsed_s": round(t["xla_branchfree"], 4),
        "bound": "round formulation only: identical campaign, driver, "
                 "schedule and outputs on all three legs — chain vs "
                 "branch-free isolates the strategy select-chain "
                 "pathology; the kernel leg is Mosaic on TPU and the "
                 "Pallas INTERPRETER elsewhere (kernel_engine names "
                 "which ran)",
        "note": "kernel_ratio_vs_branchfree on a CPU host measures the "
                "interpreter, not the kernel — the <4x "
                "flexible-vs-fused raw-speed goal is a TPU number and "
                "rides the consolidated tunnel measurement pass "
                "(ROADMAP); bit-exactness of all three legs is asserted "
                "before any timing is reported",
    }


def bench_signed_ab(jax, jnp, jr):
    """ISSUE 14: the sign-ahead lane A/B — the pipelined SIGNED sweep
    (``pipeline_sweep(signed=True)``: per-round signature tables signed
    on host in the overlap slot, verification dispatched ahead, depth-k
    megasteps in flight) vs the blocking sequential signed driver
    (``parallel.signing.sequential_signed_sweep``: sign -> verify-fetch
    -> dispatch -> fetch, per round — the ``backends._run_signed``
    shape).  Two legs, every pair bit-exact asserted (decisions,
    histograms, counters) before any timing is believed:

    1. ``interactive`` — B=1 at the interactive roster shape (capacity
       4, SM(1), exact relay): the ``run-rounds`` signed path this PR
       moves off the per-round fallback.  Engine overheads (per-round
       dispatch + fetches + host bookkeeping) dominate here, which is
       exactly what the pipeline removes — the CPU-measurable win, and
       the gated acceptance number (``interactive_speedup_within_target``
       >= 1.5x).
    2. ``sweep`` — the ``sweep10k_signed`` discipline (power-of-two
       capacity, m=3, collapsed relay) at an env-scaled batch
       (``BA_TPU_BENCH_SIGNED_BATCH``, default 2048; 10240 restores the
       full production shape).  On a CPU host this leg is HOST-VERIFY
       BOUND: the native Ed25519 batch verifier runs ~11k sigs/s on one
       core and both legs pay it identically, so the speedup reads ~1x
       BY CONSTRUCTION — there is no second core for the lane to
       overlap into and no async device verify queue.  The number is
       reported honestly (not gated); the overlap reading at this shape
       is a TPU number (device-side chunked verify + host signing off
       the critical path) and rides the consolidated tunnel measurement
       pass (ROADMAP).  ``host_sign_fraction``/``host_verify_fraction``
       decompose the sequential wall so the artifact shows WHERE the
       single-core wall sits.
    """
    import numpy as np

    from ba_tpu.parallel import fresh_copy, make_sweep_state
    from ba_tpu.parallel.pipeline import pipeline_sweep
    from ba_tpu.parallel.signing import SignAheadLane, sequential_signed_sweep

    depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
    reps = 3

    def ab(B, cap, m, collapsed, rounds, rpd, seed):
        state = make_sweep_state(make_key(seed), B, cap)
        key = make_key(seed + 1)
        lane = SignAheadLane(B, seed=0)

        def run_seq():
            return sequential_signed_sweep(
                key, state, rounds, m=m, collapsed=collapsed, lane=lane
            )

        def run_pipe():
            return pipeline_sweep(
                key, fresh_copy(state), rounds, signed=True, m=m,
                collapsed=collapsed, depth=depth,
                rounds_per_dispatch=rpd, collect_decisions=True,
            )

        # Warm + verify off the clock: compiles, the chunk-shaped verify
        # program, and the bit-exactness gate.
        ref = run_seq()
        out = run_pipe()
        bit = (
            np.array_equal(out["histograms"], ref["histograms"])
            and np.array_equal(out["decisions"], ref["decisions"])
            and out["counters"] == ref["counters"]
        )
        t_seq = t_pipe = float("inf")
        last_pipe = None
        for _ in range(reps):  # interleaved pairs: window drift cancels
            t0 = time.perf_counter()
            ref = run_seq()
            t_seq = min(t_seq, time.perf_counter() - t0)
            t0 = time.perf_counter()
            last_pipe = run_pipe()
            t_pipe = min(t_pipe, time.perf_counter() - t0)
        return {
            "batch": B, "n_max": cap, "m": m, "collapsed": collapsed,
            "rounds": rounds, "rounds_per_dispatch": rpd,
            "seq_s": round(t_seq, 4), "pipe_s": round(t_pipe, 4),
            "speedup": round(t_seq / t_pipe, 3),
            "rounds_per_sec": round(B * rounds / t_pipe, 1),
            "seq_rounds_per_sec": round(B * rounds / t_seq, 1),
            "bit_exact": bool(bit),
            "seq_timings": ref["timings"],
            "sign_ahead_s": last_pipe["stats"]["sign_ahead_s"],
            "host_sign_fraction": round(
                ref["timings"]["sign_s"] / t_seq, 4
            ),
            "host_verify_fraction": round(
                ref["timings"]["verify_s"] / t_seq, 4
            ),
        }

    interactive = ab(1, 4, 1, False, 64, 8, 50)
    sweep_batch = int(os.environ.get("BA_TPU_BENCH_SIGNED_BATCH", 2048))
    sweep_cap = int(os.environ.get("BA_TPU_BENCH_SIGNED_CAP", 256))
    sweep_rounds = int(os.environ.get("BA_TPU_BENCH_SIGNED_ROUNDS", 16))
    sweep = ab(sweep_batch, sweep_cap, 3, True, sweep_rounds, 8, 52)
    target = 1.5
    return {
        "rounds_per_sec": interactive["rounds_per_sec"],
        "interactive": interactive,
        "sweep": sweep,
        "interactive_speedup": interactive["speedup"],
        "sweep_speedup": sweep["speedup"],
        "speedup_target": target,
        "bit_exact_interactive": interactive["bit_exact"],
        "bit_exact_sweep": sweep["bit_exact"],
        "interactive_speedup_within_target": bool(
            interactive["speedup"] >= target
        ),
        "elapsed_s": interactive["pipe_s"],
        "bound": "protocol lane only: identical key schedule, round "
                 "tables and outputs on both legs — the delta is the "
                 "sequential driver's per-round sign -> verify-fetch -> "
                 "dispatch -> fetch serialization vs the lane's "
                 "windowed sign-ahead + depth-k megasteps",
        "note": "the sweep leg on a CPU host is single-core "
                "host-verify-bound (~11k sigs/s native): both legs pay "
                "the identical Ed25519 wall and the speedup reads ~1x "
                "by construction — the overlap win at the production "
                "shape is a TPU number (device verify queue + host "
                "signing off the critical path) and rides the "
                "consolidated tunnel measurement pass; the gated "
                "acceptance number is the interactive leg, where the "
                "engine overheads the pipeline removes dominate",
    }


def bench_signed_throughput(jax, jnp, jr):
    """ISSUE 16: the host-crypto wall A/B — the sweep-discipline SIGNED
    pipeline (``pipeline_sweep(signed=True)``) run as five legs that
    differ ONLY in the sign-ahead lane's host-crypto configuration,
    every leg bit-exact asserted against the in-process baseline
    (decisions, histograms, counters — including ``sig_rejections`` /
    ``commander_equivocations``) before any timing is believed:

    1. ``inproc``   — ``BA_TPU_SIGN_POOL=0 BA_TPU_SIGN_CACHE=0``: the
       single-core baseline every other leg's speedup is against.
    2. ``pool1/2/4`` — the subprocess signing/verify pool at 1/2/4
       workers, cache off (cold crypto every rep).  On a multi-core
       host these legs scale the ~11k-sigs/s/core Ed25519 wall with
       worker count; on a 1-core container they pin the sharded path's
       bit-exactness and report the (honest) pipe overhead.
    3. ``cache_warm`` — pool off, signature-table cache on, timed
       AFTER a populating run: repeat traffic under the shared sign
       seed (the serving front-end's signed-cohort shape) skips sign
       AND host verify bit-exactly by Ed25519 determinism.

    The acceptance booleans gated by the trajectory sentinel:
    ``pool_bit_exact`` (every pooled leg byte-identical, run outputs
    AND a direct signature-table + verdict-plane comparison),
    ``cache_bit_exact`` (same for the warm-cache leg), and
    ``speedup_ge_3x`` (the best leg >= 3x the in-process baseline —
    on a 1-core host that leg is the warm cache, which is the point:
    the wall breaks on repeat traffic even before cores help).
    ``host_sign_fraction``/``host_verify_fraction`` decompose every
    leg's wall so the artifact shows WHERE the crypto went.
    """
    import numpy as np

    from ba_tpu.crypto import pool as pool_mod
    from ba_tpu.crypto.signed import _round_table_msgs
    from ba_tpu.parallel import fresh_copy, make_sweep_state
    from ba_tpu.parallel.pipeline import pipeline_sweep
    from ba_tpu.parallel.signing import SignAheadLane

    B = int(os.environ.get("BA_TPU_BENCH_SIGNED_BATCH", 1024))
    cap = int(os.environ.get("BA_TPU_BENCH_SIGNED_CAP", 256))
    rounds = int(os.environ.get("BA_TPU_BENCH_SIGNED_ROUNDS", 12))
    depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
    rpd, m, collapsed, seed = 4, 3, True, 52
    reps = 2

    state0 = make_sweep_state(make_key(seed), B, cap)
    key = make_key(seed + 1)

    def run_pipe():
        return pipeline_sweep(
            key, fresh_copy(state0), rounds, signed=True, m=m,
            collapsed=collapsed, depth=depth, rounds_per_dispatch=rpd,
            collect_decisions=True,
        )

    saved = {
        k: os.environ.get(k)
        for k in ("BA_TPU_SIGN_POOL", "BA_TPU_SIGN_CACHE")
    }
    legs, ref_out = {}, None
    try:
        for name, pool_env, cache_env in (
            ("inproc", "0", "0"),
            ("pool1", "1", "0"),
            ("pool2", "2", "0"),
            ("pool4", "4", "0"),
            ("cache_warm", "0", "256"),
        ):
            os.environ["BA_TPU_SIGN_POOL"] = pool_env
            os.environ["BA_TPU_SIGN_CACHE"] = cache_env
            pool_mod.shutdown_defaults()
            # Off the clock: compiles, the pool spawn, and (the
            # cache_warm leg's whole point) the cache-populating pass.
            out = run_pipe()
            if ref_out is None:
                ref_out = out
            t = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                out = run_pipe()
                t = min(t, time.perf_counter() - t0)
            bit = (
                np.array_equal(out["histograms"], ref_out["histograms"])
                and np.array_equal(out["decisions"], ref_out["decisions"])
                and out["counters"] == ref_out["counters"]
            )
            st = out["stats"]
            legs[name] = {
                "wall_s": round(t, 4),
                "bit_exact": bool(bit),
                "pool_workers": st["sign_pool_workers"],
                "pool_s": st["sign_pool_s"],
                "cache_hits": st["sign_cache_hits"],
                "host_sign_s": st["host_sign_s"],
                "host_verify_s": st["host_verify_s"],
                "host_sign_fraction": round(st["host_sign_s"] / t, 4),
                "host_verify_fraction": round(st["host_verify_s"] / t, 4),
                "rounds_per_sec": round(B * rounds / t, 1),
            }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        pool_mod.shutdown_defaults()

    base = legs["inproc"]["wall_s"]
    for leg in legs.values():
        leg["speedup"] = round(base / leg["wall_s"], 3)

    # Direct table/plane bit-exactness, below the engine: the pooled
    # and cached lanes must reproduce the in-process lane's signature
    # TABLES and verdict PLANES byte-for-byte, not just the verdicts
    # the sweep consumed.  (The cache doubles as the window into the
    # pooled signatures.)
    lane_b, lane_v, lane_seed = 8, 2, 7
    wins = [(0, 4), (4, 6)]
    ref_lane = SignAheadLane(lane_b, seed=lane_seed, pool=0, cache=0)
    ref_planes = [np.asarray(p) for p in ref_lane.stage_windows(wins)]
    ref_sigs = [ref_lane.round_tables(r)[1] for r in range(6)]
    pool2 = pool_mod.SignPool(2)
    try:
        pcache = pool_mod.SigTableCache(64)
        pool_lane = SignAheadLane(
            lane_b, seed=lane_seed, pool=pool2, cache=pcache
        )
        pool_planes = [np.asarray(p) for p in pool_lane.stage_windows(wins)]
        tables_exact = all(
            np.array_equal(
                pcache.get(
                    pool_mod.SigTableCache.round_key(
                        pool_lane.pks,
                        _round_table_msgs(lane_b, r, lane_v, 0),
                    )
                )[0],
                ref_sigs[r],
            )
            for r in range(6)
        )
        # The warm replay: a SECOND staging over the same cache must
        # be pure hits and byte-identical planes.
        warm_planes = [np.asarray(p) for p in pool_lane.stage_windows(wins)]
    finally:
        pool2.close()
    planes_pool_exact = all(
        np.array_equal(a, b) for a, b in zip(ref_planes, pool_planes)
    )
    planes_warm_exact = all(
        np.array_equal(a, b) for a, b in zip(ref_planes, warm_planes)
    )

    best = max(legs, key=lambda n: legs[n]["speedup"])
    pool_bit_exact = bool(
        all(legs[n]["bit_exact"] for n in ("pool1", "pool2", "pool4"))
        and planes_pool_exact
        and tables_exact
    )
    cache_bit_exact = bool(
        legs["cache_warm"]["bit_exact"] and planes_warm_exact
    )
    return {
        "rounds_per_sec": legs[best]["rounds_per_sec"],
        "elapsed_s": legs[best]["wall_s"],
        "batch": B, "n_max": cap, "m": m, "collapsed": collapsed,
        "rounds": rounds, "rounds_per_dispatch": rpd,
        "legs": legs,
        "best_leg": best,
        "best_speedup": legs[best]["speedup"],
        "pool_bit_exact": pool_bit_exact,
        "cache_bit_exact": cache_bit_exact,
        "speedup_ge_3x": bool(legs[best]["speedup"] >= 3.0),
        "bound": "host-crypto lane only: identical key schedule, round "
                 "tables, verdict planes and sweep outputs on every leg "
                 "— the delta is WHO runs the Ed25519 wall (one core, N "
                 "worker processes, or nobody on a warm cache hit)",
        "note": "pool legs on a 1-core container pin bit-exactness and "
                "honest pipe overhead (no second core to scale into); "
                "the >=3x acceptance leg there is cache_warm — repeat "
                "signed cohorts under the shared sign seed, the serving "
                "front-end's steady state",
    }


def bench_adversary_search(jax, jnp, jr):
    """Adversary-search config (ISSUE 15 acceptance): a seeded
    CI-sized hunt — random populations of candidate campaigns lowered
    campaign-per-instance and evaluated batched through the coalesced
    engine — must (a) sustain a candidate-campaign throughput worth
    brute-forcing with, and (b) FIND at least one IC1/IC2-violating
    campaign, shrink it to a minimal event set, and reproduce the
    violation bit-exactly when the shrunk spec replays standalone
    (the alone-vs-in-population parity oracle).

    Throughput is read from the steady-state generations (the
    per-generation walls after generation 0's compile), reported both
    as campaigns/s and campaign-rounds/s; ``found_violation_rate`` is
    the random sweep's hit rate over the whole hunt.  The two
    acceptance booleans are gated by the trajectory sentinel:
    ``found_violation_ok`` (the hunt found and minimized >= 1
    violation) and ``shrunk_replay_bit_exact`` (every minimized
    finding passed the parity oracle).
    """
    from ba_tpu.search.generate import SearchSpace
    from ba_tpu.search.loop import hunt

    population = int(os.environ.get("BA_TPU_BENCH_SEARCH_POP", 256))
    capacity = int(os.environ.get("BA_TPU_BENCH_SEARCH_CAP", 16))
    rounds = int(os.environ.get("BA_TPU_BENCH_SEARCH_ROUNDS", 8))
    generations = int(os.environ.get("BA_TPU_BENCH_SEARCH_GENS", 4))
    space = SearchSpace(
        rounds=rounds, capacity=capacity, population=population,
        events_min=2, events_max=6,
    )
    gen_walls = []
    t0 = time.perf_counter()
    out = hunt(
        space, seed=41, generations=generations, objective="ic",
        minimize=True, minimize_max=2,
        on_generation=lambda g, info: gen_walls.append(
            time.perf_counter()
        ),
    )
    elapsed = time.perf_counter() - t0
    # Steady-state generation wall: the narrowest gap between
    # consecutive generation completions (generation 0 pays the
    # megastep compiles; later generations are pure dispatch streams).
    steady = min(
        (b - a for a, b in zip(gen_walls, gen_walls[1:])),
        default=elapsed,
    )
    stats = out["stats"]
    minimized = out["minimized"]
    return {
        "rounds_per_sec": round(population * rounds / steady, 1),
        "campaigns_per_sec": round(population / steady, 1),
        "population": population,
        "capacity": capacity,
        "rounds": rounds,
        "generations": generations,
        "campaigns": stats["campaigns"],
        "found": stats["found"],
        "found_violation_rate": round(
            stats["found"] / stats["campaigns"], 4
        ),
        "best_score": stats["best_score"],
        "minimized_events": [
            [m["events_before"], m["events_after"]] for m in minimized
        ],
        "minimize_evals": sum(m["evals"] for m in minimized),
        "found_violation_ok": stats["found"] >= 1 and len(minimized) >= 1,
        "shrunk_replay_bit_exact": bool(minimized)
        and all(m["bit_exact"] for m in minimized),
        "objective": "ic",
        "elapsed_s": round(elapsed, 4),
        "steady_generation_s": round(steady, 4),
        "bound": "population evaluation is one coalesced scenario "
                 "dispatch stream (per-slot keys + per-slot counter "
                 "blocks), so campaigns/s is the engine's batched "
                 "mutating-round throughput divided by rounds; "
                 "generation 0 additionally pays the megastep compiles",
        "note": "seeded hunt (seed 41): sample -> evaluate -> elite "
                "mutation over the spec grammar; findings ddmin-shrunk "
                "and re-validated by the alone-vs-in-population "
                "bit-exact replay oracle (the serving parity pin).  "
                "CPU artifact BENCH_search_r15.json",
    }


CONFIGS = {
    # Latency-sensitive configs first: dispatch through the TPU tunnel gets
    # noticeably slower once the big Ed25519-verify programs have run
    # (measured r2: config #4 drops ~100x when sequenced after #3).
    "interactive_b1": bench_interactive_b1,
    "om1_n4": bench_om1_n4,
    "om3_n10": bench_om3_n10,
    "n1024_m32": bench_n1024_m32,
    "eig_n1024": bench_eig_n1024,
    "failover_sweep": bench_failover_sweep,
    "pipeline_sweep": bench_pipeline_sweep,
    "scenario_sweep": bench_scenario_sweep,
    "megastep_ab": bench_megastep_ab,
    "signed_ab": bench_signed_ab,
    "signed_throughput": bench_signed_throughput,
    "scenario_long": bench_scenario_long,
    "resilience": bench_resilience,
    "serving": bench_serving,
    "serving_warm": bench_serving_warm,
    "serving_slo": bench_serving_slo,
    "fleet_trace": bench_fleet_trace,
    "serving_fleet": bench_serving_fleet,
    "multichip": bench_multichip,
    "sweep10k_signed": bench_sweep10k_signed,
    "sm1_n64_signed": bench_sm1_n64_signed,
    "adversary_search": bench_adversary_search,
}

# scenario_long runs a quarter-million-round campaign (minutes of wall
# clock by design), resilience SIGKILLs a child process that pays a
# fresh jax import + compile, multichip spawns forced-8-device
# children (the device count must precede jax init), serving runs
# a deliberately-overloaded client-fleet drill (thread storms, 50 ms
# stalls per dispatch), serving_warm pays a full AOT warmup pass
# plus a deliberately-cold comparison leg, megastep_ab re-traces
# the legacy strategy formulation per rep + runs the Pallas interpreter
# leg (minutes of compile/interpretation by design), and
# adversary_search runs a multi-generation hunt whose minimizer replays
# dozens of shrink trials, signed_throughput runs the signed sweep
# five times over (pool spawns + a cache-populating pass per leg), and
# serving_slo sleeps through real burn windows (quiet gap + recovery)
# around a deadline-storm burst, fleet_trace pays a warm AOT pass
# plus a sign-pool respawn in sink-directory mode, and serving_fleet
# warm-boots FOUR replicas across its two legs plus a multi-thousand-
# round kill-and-adopt campaign drill —
# all opt in explicitly: `--configs scenario_long` / `resilience` /
# `multichip` / `serving` / `serving_warm` / `serving_slo` /
# `fleet_trace` / `serving_fleet` / `megastep_ab` /
# `adversary_search` / `signed_throughput`.
DEFAULT_CONFIGS = [
    n for n in CONFIGS
    if n not in (
        "scenario_long", "resilience", "multichip", "serving",
        "serving_warm", "serving_slo", "fleet_trace", "serving_fleet",
        "megastep_ab", "signed_ab", "adversary_search",
        "signed_throughput",
    )
]


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--profile", metavar="DIR", default=None,
                        help="write a jax.profiler trace to DIR (works on "
                             "local backends, e.g. BA_TPU_BENCH_PLATFORM=cpu "
                             "or directly-attached TPU; the shared TPU-tunnel "
                             "backend does not serve the profiler and hangs)")
    parser.add_argument("--xprof", metavar="DIR",
                        default=os.environ.get("BA_TPU_XPROF") or None,
                        help="capture a jax.profiler device trace of the "
                             "run into DIR (view with TensorBoard/xprof); "
                             "megastep dispatch/retire carry "
                             "TraceAnnotation markers aligning the device "
                             "timeline with the host spans (--obs).  "
                             "BA_TPU_XPROF=DIR is the env spelling.  Same "
                             "caveat as --profile: the shared TPU-tunnel "
                             "backend does not serve the profiler")
    parser.add_argument("--obs", metavar="DIR", default=None,
                        help="write HOST observability artifacts to DIR "
                             "(ba_tpu.obs): trace.json — Chrome trace-event "
                             "spans (compile/dispatch/retire/host_work, "
                             "Perfetto-loadable), metrics.jsonl — the JSONL "
                             "sink incl. the final metrics_snapshot record, "
                             "metrics.prom — Prometheus text exposition.  "
                             "Orthogonal to --profile (device kernels) and "
                             "safe on every backend; render with "
                             "scripts/obs_report.py DIR")
    parser.add_argument("--configs", default=os.environ.get(
        "BA_TPU_BENCH_CONFIGS", ",".join(DEFAULT_CONFIGS)),
        help="comma-separated subset of: " + ",".join(CONFIGS)
             + " (scenario_long and resilience are opt-in: a >=100k-round"
             " campaign / a child-process SIGKILL drill)")
    parser.add_argument("--stages", action="store_true",
                        help="per-stage verify-pipeline breakdown + VPU "
                             "int32 peak instead of the config suite; "
                             "prints its own single JSON line")
    args = parser.parse_args()

    platform = os.environ.get("BA_TPU_BENCH_PLATFORM")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if args.obs:
        # Force-enable the host tracer + route the JSONL sink into the
        # obs dir BEFORE any jit compiles, so first-call "compile" spans
        # land in the trace; artifacts are finalized by _obs_finalize.
        os.makedirs(args.obs, exist_ok=True)
        from ba_tpu import obs as _obs
        from ba_tpu.utils import metrics as _metrics

        _obs.default_tracer().enabled = True
        # Crash-safe artifacts: finalize at exit too (idempotent), so an
        # OOM'd/interrupted campaign still leaves its trace behind.
        import atexit

        atexit.register(
            _obs_finalize, args.obs, jax.devices()[0].platform
        )
        if os.environ.get("BA_TPU_METRICS"):
            # --obs owns the artifact dir contract; say so rather than
            # silently starving a user-configured sink of records.
            print(
                f"bench: --obs overrides BA_TPU_METRICS="
                f"{os.environ['BA_TPU_METRICS']!r} for this run (JSONL -> "
                f"{os.path.join(args.obs, 'metrics.jsonl')})",
                file=sys.stderr,
            )
        _metrics.configure(os.path.join(args.obs, "metrics.jsonl"))
        # One run scope for the whole bench invocation (ISSUE 9):
        # BA_TPU_RUN_ID pins it, else it derives from the config list —
        # every record/span below carries the id, inner sweeps inherit,
        # and _obs_finalize assembles ONE flight summary at exit.
        _metrics.set_run_id(
            _obs.flight.resolve_run_id("bench", args.configs)
        )
    # Persistent XLA cache: repeat bench invocations (bench_refresh.sh
    # attempts, A/B scripts) stop re-paying unchanged programs' compiles.
    # Compile time was never inside the timed loops, so cached-vs-fresh
    # does not move any reported number.
    from ba_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()
    import jax.numpy as jnp
    import jax.random as jr

    from ba_tpu.core.rng import rng_impl

    if args.stages:
        line = {
            "metric": "verify-stage-breakdown",
            "platform": jax.devices()[0].platform,
            "rng_impl": rng_impl(),
            "vpu_int32_peak": bench_vpu_int32_peak(jax, jnp, jr),
            "fieldmul_peak": bench_fieldmul_peak(jax, jnp, jr),
            "stages": bench_verify_stages(jax, jnp, jr),
        }
        if args.obs:
            _obs_finalize(args.obs, jax.devices()[0].platform)
        print(json.dumps(line))
        return

    if args.profile and args.xprof:
        # jax.profiler allows ONE active session: the second start_trace
        # would raise mid-run with the first trace already open.
        parser.error("--profile and --xprof cannot be combined "
                     "(one jax.profiler session at a time)")
    trace = (jax.profiler.trace(args.profile) if args.profile
             else contextlib.nullcontext())
    from ba_tpu import obs as _obs_xprof

    xprof = (_obs_xprof.xla.xprof_session(args.xprof) if args.xprof
             else contextlib.nullcontext())
    names = [n.strip() for n in args.configs.split(",") if n.strip()]
    unknown = [n for n in names if n not in CONFIGS]
    if not names or unknown:
        parser.error(
            f"unknown config(s) {unknown or args.configs!r}; "
            f"valid: {', '.join(CONFIGS)}"
        )
    results = {}
    with trace, xprof:
        for name in names:
            print(f"bench: {name} ...", file=sys.stderr, flush=True)
            results[name] = CONFIGS[name](jax, jnp, jr)
    if args.obs:
        _obs_finalize(args.obs, jax.devices()[0].platform)

    primary_name = "om1_n4" if "om1_n4" in results else next(iter(results))
    primary = results[primary_name]
    unit = "rounds/s (%s)" % (
        "OM(1), n=4, 1 traitor, B=%d" % primary.get("batch", 0)
        if primary_name == "om1_n4"
        else primary_name
    )
    line = {
        "metric": "agreement-rounds/sec",
        "value": primary["rounds_per_sec"],
        "unit": unit,
        "vs_baseline": round(
            primary["rounds_per_sec"] / REFERENCE_ROUNDS_PER_SEC, 1
        ),
        "platform": jax.devices()[0].platform,
        "rng_impl": rng_impl(),
        "hbm_peak_gbps_assumed": HBM_PEAK_GBPS,
        "variance_note": "shared TPU service: ~2x run-to-run noise; "
                         "min-of-3 per config applied.  All timings are "
                         "host-fetch-synced (jax.device_get): r2 found "
                         "block_until_ready on this backend acks the "
                         "dispatch without awaiting execution, so earlier "
                         "rounds' numbers for dispatch-bound configs were "
                         "enqueue rates, not throughput",
        "configs": results,
    }
    if "sweep10k_signed" in results:
        line["north_star_rounds_per_sec"] = results["sweep10k_signed"][
            "rounds_per_sec"
        ]
    if "sm1_n64_signed" in results:
        line["ed25519_verifies_per_sec"] = results["sm1_n64_signed"][
            "ed25519_verifies_per_sec"
        ]

    # Output contract (driver round 3 regression: the full per-config line
    # outgrew the driver's stdout tail window, so its recorded artifact had
    # parsed=null and the numbers had to be text-scraped).  The FINAL stdout
    # line is a compact headline object guaranteed to fit any tail window;
    # the full per-config detail goes to a JSON file plus stderr.
    detail_path = os.environ.get("BA_TPU_BENCH_DETAIL", "BENCH_detail.json")
    with open(detail_path, "w") as f:
        json.dump(line, f)
    print(json.dumps(line), file=sys.stderr)
    compact = {
        "metric": line["metric"],
        "value": line["value"],
        "unit": line["unit"],
        "vs_baseline": line["vs_baseline"],
        "platform": line["platform"],
        "rng_impl": line["rng_impl"],
        "detail_file": detail_path,
    }
    for k in ("north_star_rounds_per_sec", "ed25519_verifies_per_sec"):
        if k in line:
            compact[k] = line[k]
    sweep = results.get("sweep10k_signed")
    if sweep:
        compact["incl_setup_crossover_1M_iters"] = sweep[
            "incl_setup_crossover_1M_iters"
        ]
        compact["setup_verify_s"] = sweep["setup_verify_s"]
        # Window-spread disclosure (VERDICT r4 item 6): fold the attempt
        # log's north-star rates (bench_refresh.sh appends one per
        # attempt) plus THIS run into n/min/median/max, so the driver
        # artifact carries the distribution, not just a point estimate.
        import glob

        # Numeric round sort: lexicographic would order r10 before r4.
        logs = sorted(
            glob.glob("BENCH_attempts_r*.jsonl"),
            key=lambda p: int(p.rsplit("_r", 1)[1].split(".")[0]),
        )
        log = os.environ.get(
            "BA_TPU_BENCH_ATTEMPTS_LOG", logs[-1] if logs else ""
        )
        rates = [sweep["rounds_per_sec"]]
        if log and os.path.exists(log):
            for rec in open(log):
                try:
                    rates.append(
                        json.loads(rec)["configs"]["sweep10k_signed"][
                            "rounds_per_sec"
                        ]
                    )
                except (ValueError, KeyError):
                    pass
        rates.sort()
        compact["north_star_window"] = {
            "n": len(rates),
            "min": rates[0],
            "median": rates[len(rates) // 2],
            "max": rates[-1],
            "log": log or None,
            "note": "incl. this run",
        }
    print(json.dumps(compact))


if __name__ == "__main__":
    main()
